"""Layout autotuner: ``init(parallel="auto")`` — enumerate, prune, trial, bank.

PR 15 made N-D layouts declarative (one :class:`ParallelConfig` → one
mesh + strict partition rules) but a human still picked ``dp × fsdp ×
tp`` per model and pod shape — and the per-axis bench legs prove the
choice is workload-dependent (fsdp ~free, tp −28% at toy scale on CPU),
not guessable. This module closes ROADMAP open item 3: a four-stage
search that needs no human in the loop and no framework coupling beyond
the one ``init`` kwarg.

Stage 1 — **enumerate** (:func:`enumerate_candidates`): every ordered
``dp × fsdp × tp`` factorization of the device count. ``pp``/``sp``/
``ep`` are out of the v1 search space on purpose — both need model
surgery (staged apply / attention-fn wiring) no generic trial can
perform; pin those by hand (docs/performance.md, "Auto layout").
Validity is *inherited*, not re-implemented: each candidate resolves
through :meth:`ParallelConfig.resolve` (axes must cover the devices)
and lays the params out through the plan's own strict rule path — a
``tp`` candidate whose Megatron table had to warn-and-degrade (a dim
the axis does not divide) is invalid, as is an ``fsdp`` candidate whose
ZeRO rule claimed nothing (every leaf under ``fsdp_min_size``).

Stage 2 — **prune without executing**: a static per-layout memory model
(:func:`layout_bytes` — param + optax-state + gradient bytes per device
from the same leaf walk the checkpoint manifest uses) checked against
the memory plane's ``bytes_limit``, then a relative compute/comms score
from the AOT-lowered update step's XLA cost analysis
(:func:`~fluxmpi_tpu.utils.flops.executable_cost` — ``lower().compile()``
reads only avals: nothing is placed, nothing runs). Memory-infeasible
candidates die first (``pruned="memory"``), then everything the static
ranking places past the trial budget (``pruned="dominated"``) — with
the pure-dp baseline always kept for the trials to beat.

Stage 3 — **profile** (:func:`_run_trial`): each survivor (≤
``FLUXMPI_TPU_AUTOTUNE_TRIALS``, default 4) runs short fused-window
trials through the real ``train_loop(fuse="window")`` machinery on
seeded synthetic batches — a warmup epoch pays the window compile
(booked to the goodput compile bucket and attributed by the compile
monitor, exactly like production), then a timed run that must be a pure
window-cache hit: zero steady-state retraces, zero new compiles. The
throughput winner is selected.

Stage 4 — **bank**: winner + the full candidate table become a schema'd
``fluxmpi_tpu.autotune/v1`` record — validated before it is trusted —
kept in-process, optionally in the ``FLUXMPI_TPU_AUTOTUNE_BANK`` JSON
file, and written next to the checkpoint manifest by every
``save_checkpoint`` under an autotuned plan. A later ``autotune()``
with the same (model fingerprint, topology) reuses the banked winner
and skips the trials entirely; a topology change (elastic resume onto a
different slice) misses the bank and re-tunes instead of crashing.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
import warnings
from typing import Any, Sequence

import numpy as np

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..telemetry.schema import (
    AUTOTUNE_PRUNE_REASONS,
    AUTOTUNE_SCHEMA,
    validate_autotune_record,
)
from .plan import ParallelConfig, ResolvedPlan

__all__ = [
    "AutotuneResult",
    "autotune",
    "clear_bank",
    "enumerate_candidates",
    "layout_bytes",
    "model_fingerprint",
]

TRIALS_ENV = "FLUXMPI_TPU_AUTOTUNE_TRIALS"
BANK_ENV = "FLUXMPI_TPU_AUTOTUNE_BANK"

_DEFAULT_TRIALS = 4

# Score weighting: one HBM byte accessed costs about as much as four
# FLOPs at the arithmetic intensity where TPU matmuls stop being
# compute-bound — heavier traffic (all-gathers, reduce-scatters the
# partitioner inserted) should lose to an equal-FLOPs layout that keeps
# data local. The score only RANKS candidates of one model on one
# topology, so the constant's absolute calibration does not matter.
_BYTE_COST_FLOPS = 4.0

# In-process bank: (model fingerprint, topology key) → banked record.
# Survives shutdown()/init() cycles on purpose — re-tuning because a
# test re-initialized the runtime would make every auto run pay the
# trials twice in one process.
_BANK: dict[tuple[str, str], dict[str, Any]] = {}

# The record of the last completed (or bank-reused) tune in this
# process — what save_checkpoint's sidecar write reads.
_LAST_RECORD: dict[str, Any] | None = None


class Candidate:
    """One enumerated layout: its axes, resolved plan, and the evidence
    the stages attach (memory, static score, trial result, prune
    reason)."""

    def __init__(self, axes: dict[str, int], plan: ResolvedPlan):
        self.axes = axes
        self.plan = plan
        self.mem_bytes_per_device: int | None = None
        self.flops: float | None = None
        self.bytes_accessed: float | None = None
        self.score: float | None = None
        self.pruned: str | None = None
        self.trial: dict[str, Any] | None = None

    def describe(self) -> dict[str, Any]:
        return {
            "axes": dict(self.axes),
            "mem_bytes_per_device": self.mem_bytes_per_device,
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "score": self.score,
            "pruned": self.pruned,
            "trial": self.trial,
        }


class AutotuneResult:
    """What :func:`autotune` returns: the winning resolved plan (carrying
    ``autotune_fingerprint``), the schema'd record, and whether the bank
    answered (``from_bank=True`` → zero trials ran)."""

    def __init__(
        self, plan: ResolvedPlan, record: dict[str, Any], from_bank: bool
    ):
        self.plan = plan
        self.record = record
        self.from_bank = from_bank

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        axes = ", ".join(
            f"{a}={s}" for a, s in self.record["winner"]["axes"].items()
            if s != 1
        )
        src = "bank" if self.from_bank else "trials"
        return f"AutotuneResult({axes or 'dp=1'}, from {src})"


# ---------------------------------------------------------------------------
# Identity: what makes a banked winner reusable.
# ---------------------------------------------------------------------------


def model_fingerprint(params: Any) -> str:
    """Stable identity of a model's parameter tree: sha256 over the
    manifest-style leaf walk (path, shape, dtype per leaf — the same
    ingredients the checkpoint manifest records), truncated to 16 hex
    chars. Two models with identical structure tune identically, so
    this — with the topology — is the bank key."""
    from .sharding import _path_str

    rows = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        shape = tuple(int(d) for d in getattr(leaf, "shape", ()) or ())
        dtype = str(getattr(leaf, "dtype", "?"))
        rows.append(f"{_path_str(path)}:{shape}:{dtype}")
    digest = hashlib.sha256("\n".join(rows).encode("utf-8")).hexdigest()
    return digest[:16]


def topology_signature(devices: Sequence[jax.Device]) -> dict[str, Any]:
    """The topology half of the bank key: device count, kind, and the
    process world — what an elastic resume can change."""
    devs = list(devices)
    return {
        "n_devices": len(devs),
        "device_kind": str(devs[0].device_kind) if devs else "none",
        "process_count": int(jax.process_count()),
    }


def _topology_key(sig: dict[str, Any]) -> str:
    return (
        f"{sig['n_devices']}x{sig['device_kind']}"
        f"x{sig['process_count']}proc"
    )


# ---------------------------------------------------------------------------
# Stage 1: enumerate.
# ---------------------------------------------------------------------------


def _factorizations(n: int) -> list[tuple[int, int, int]]:
    """All ordered (dp, fsdp, tp) triples of positive ints with product
    ``n`` — deterministic order (dp descending: pure-dp first, the
    layout most likely to win at small scale trials first)."""
    out = []
    for dp in range(n, 0, -1):
        if n % dp:
            continue
        rest = n // dp
        for fsdp in range(rest, 0, -1):
            if rest % fsdp:
                continue
            out.append((dp, fsdp, rest // fsdp))
    return out


def enumerate_candidates(
    params: Any,
    devices: Sequence[jax.Device],
    *,
    fsdp_min_size: int = 1024,
) -> list[Candidate]:
    """Stage 1: every valid ``dp × fsdp × tp`` layout for this model on
    these devices. Validity rides the existing strict plan path — each
    candidate resolves through :meth:`ParallelConfig.resolve` and lays
    the params out through ``plan.partition_specs``; a candidate whose
    rules had to warn-and-degrade (tp axis not dividing a matched dim)
    or whose fsdp/tp axis claimed no leaf at all is dropped, so
    no-silent-replication is inherited rather than re-implemented."""
    devs = list(devices)
    out: list[Candidate] = []
    for dp, fsdp, tp in _factorizations(len(devs)):
        cfg = ParallelConfig(
            dp=dp, fsdp=fsdp, tp=tp, fsdp_min_size=fsdp_min_size
        )
        try:
            plan = cfg.resolve(devs)
        except Exception:
            continue
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            try:
                plan.partition_specs(params)
            except Exception:
                continue
        if caught:
            # The rule engine degraded something (a tp dim the axis
            # does not divide, a rank mismatch): this layout would
            # silently under-shard — not a candidate.
            continue
        if tp > 1 and not plan.rule_hits.get("tp"):
            continue
        if fsdp > 1 and not plan.rule_hits.get("fsdp"):
            # Every leaf under fsdp_min_size: the axis buys no memory,
            # only collective latency.
            continue
        out.append(Candidate({"dp": dp, "fsdp": fsdp, "tp": tp}, plan))
    return out


# ---------------------------------------------------------------------------
# Stage 2: prune without executing.
# ---------------------------------------------------------------------------


def _spec_shard_factor(spec: Any, mesh: Any) -> int:
    factor = 1
    for entry in tuple(spec or ()):
        if entry is None:
            continue
        names = entry if isinstance(entry, (tuple, list)) else (entry,)
        for name in names:
            factor *= int(mesh.shape[name])
    return factor


def _tree_bytes_per_device(tree: Any, specs: Any, mesh: Any) -> int:
    leaves = jax.tree_util.tree_leaves(tree)
    spec_leaves = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, P)
    )
    total = 0
    for leaf, spec in zip(leaves, spec_leaves):
        shape = tuple(getattr(leaf, "shape", ()) or ())
        dtype = np.dtype(getattr(leaf, "dtype", np.float32))
        nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
        total += -(-nbytes // _spec_shard_factor(spec, mesh))
    return int(total)


def state_template(
    params: Any, optimizer: Any, model_state: Any = None
) -> Any:
    """Abstract :class:`~fluxmpi_tpu.parallel.TrainState` for the memory
    model: ``jax.eval_shape`` over ``TrainState.create`` — the optax
    state's structure and dtypes without allocating a byte of it."""
    from .train import TrainState

    return jax.eval_shape(
        lambda: TrainState.create(params, optimizer, model_state)
    )


def layout_bytes(template: Any, plan: ResolvedPlan) -> int:
    """Stage 2's static memory model: steady-state training bytes per
    device under ``plan`` — the sharded :class:`TrainState` (params +
    optimizer state, laid out by the plan's own rule) plus one gradient
    tree (same layout as the params). Activations and batch staging are
    excluded (both scale with the batch the caller controls, not the
    layout) — the check against ``bytes_limit`` is a floor, which is
    exactly what infeasibility pruning needs."""
    mesh = plan.mesh
    state_specs = plan.partition_specs(template)
    total = _tree_bytes_per_device(template, state_specs, mesh)
    params = getattr(template, "params", None)
    if params is not None:
        total += _tree_bytes_per_device(
            params, plan.partition_specs(params), mesh
        )
    return total


def _sharded_avals(tree: Any, specs: Any, mesh: Any) -> Any:
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    spec_leaves = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, P)
    )
    avals = [
        jax.ShapeDtypeStruct(
            tuple(leaf.shape),
            leaf.dtype,
            sharding=NamedSharding(mesh, spec),
        )
        for leaf, spec in zip(leaves, spec_leaves)
    ]
    return jax.tree_util.tree_unflatten(treedef, avals)


def _static_cost(
    loss_fn: Any,
    optimizer: Any,
    template: Any,
    sample_batch: Any,
    plan: ResolvedPlan,
) -> dict[str, float] | None:
    """AOT-lower one full update step (grad + optimizer apply) under the
    candidate's shardings and read XLA's cost analysis — per-device
    FLOPs and bytes accessed, communication the partitioner inserted
    included. ``lower().compile()`` consumes only avals: no data is
    placed on the candidate's mesh and nothing executes.

    Pallas kernels (the flash-attention hot path) lower to opaque custom
    calls whose matmuls XLA's cost model reports as zero, so the traced
    jaxpr is walked for ``pallas_call`` equations and their analytic
    cost (:func:`~fluxmpi_tpu.utils.flops.pallas_kernel_cost`) is folded
    in, divided evenly across the mesh — attention work shards with the
    batch/heads under every dp×fsdp×tp candidate, so the per-device
    share is layout-invariant but the TOTAL is real: a kernel-heavy
    model no longer looks computation-free next to its communication."""
    import optax

    from ..utils.flops import executable_cost, pallas_kernel_cost

    mesh = plan.mesh
    state_avals = _sharded_avals(
        template, plan.partition_specs(template), mesh
    )
    batch_spec = plan.batch_spec
    batch_avals = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(
            tuple(np.shape(x)),
            getattr(x, "dtype", np.float32),
            sharding=NamedSharding(mesh, batch_spec),
        ),
        sample_batch,
    )

    def update(state, batch):
        def scalar_loss(p):
            loss, _ = loss_fn(p, state.model_state, batch)
            return loss

        grads = jax.grad(scalar_loss)(state.params)
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params
        )
        params = optax.apply_updates(state.params, updates)
        return state.replace(
            params=params, opt_state=opt_state, step=state.step + 1
        )

    try:
        compiled = jax.jit(update).lower(state_avals, batch_avals).compile()
    except Exception:
        return None
    cost = executable_cost(compiled)
    if cost is not None:
        try:
            kernel = pallas_kernel_cost(
                jax.make_jaxpr(update)(state_avals, batch_avals)
            )
        except Exception:  # pragma: no cover - cost stays XLA-only
            kernel = None
        if kernel:
            ndev = float(mesh.devices.size) or 1.0
            cost["flops"] += kernel["flops"] / ndev
            cost["bytes_accessed"] += kernel["bytes_accessed"] / ndev
    return cost


def _score(cost: dict[str, float] | None) -> float | None:
    if not cost:
        return None
    flops = cost.get("flops") or 0.0
    bytes_accessed = cost.get("bytes_accessed") or 0.0
    if flops <= 0 and bytes_accessed <= 0:
        return None
    return flops + _BYTE_COST_FLOPS * bytes_accessed


def _prune(
    candidates: list[Candidate], *, bytes_limit: int | None, max_trials: int
) -> list[Candidate]:
    """Stage 2's verdict. Memory-infeasible layouts die first
    (``pruned="memory"``); the rest are ranked by the static cost score
    (ties broken by the memory floor, then axes — deterministic) and
    everything past the trial budget is ``pruned="dominated"``. The
    pure-dp layout, when feasible, is always among the survivors: it is
    the zero-collective baseline every other layout must beat on the
    clock, and the static score — a relative model, not a measurement —
    must not be allowed to silence it. Returns the survivors
    best-score-first."""
    for cand in candidates:
        if (
            bytes_limit
            and cand.mem_bytes_per_device is not None
            and cand.mem_bytes_per_device > bytes_limit
        ):
            cand.pruned = "memory"
    alive = [c for c in candidates if c.pruned is None]

    def sort_key(c: Candidate) -> tuple:
        return (
            c.score if c.score is not None else float("inf"),
            c.mem_bytes_per_device or 0,
            tuple(sorted(c.axes.items())),
        )

    alive.sort(key=sort_key)
    survivors = alive[:max_trials]
    pure_dp = next(
        (
            c
            for c in alive
            if all(s == 1 for a, s in c.axes.items() if a != "dp")
        ),
        None,
    )
    if pure_dp is not None and pure_dp not in survivors:
        survivors[-1] = pure_dp
    for cand in alive:
        if cand not in survivors:
            cand.pruned = "dominated"
    return survivors


# ---------------------------------------------------------------------------
# Stage 3: profile — fused-window trials on the real train_loop.
# ---------------------------------------------------------------------------


def _trial_dataset(sample_batch: Any, window: int, seed: int) -> Any:
    """``window`` seeded shuffles of the sample batch, concatenated —
    every candidate trains on the identical synthetic stream."""
    rng = np.random.default_rng(seed)
    lead = int(np.shape(jax.tree_util.tree_leaves(sample_batch)[0])[0])
    perms = [rng.permutation(lead) for _ in range(window)]
    return jax.tree_util.tree_map(
        lambda x: np.concatenate([np.asarray(x)[p] for p in perms]),
        sample_batch,
    )


def _run_trial(
    loss_fn: Any,
    optimizer: Any,
    host_params: Any,
    model_state: Any,
    sample_batch: Any,
    plan: ResolvedPlan,
    *,
    window: int,
    epochs: int,
    seed: int,
) -> dict[str, Any]:
    """One candidate's fused-window trial: place a fresh state under the
    plan, build the real ``make_train_step(parallel=plan)``, and drive
    ``train_loop(fuse="window")`` twice — a warmup epoch that pays the
    window AOT compile (booked to the goodput compile bucket and
    attributed by the compile monitor, like any production run), then
    the timed epochs, which must be a pure window-cache hit: zero new
    compiles, zero steady-state retraces. This is the module's ONE trial
    entry point — tests monkeypatch it (explode to prove a bank hit ran
    no trial; stub to make winner selection deterministic)."""
    from ..data import ArrayDataset, DistributedDataLoader
    from ..telemetry.compileplane import get_compile_monitor
    from .loop import train_loop
    from .train import TrainState, make_train_step, replicate

    t0 = time.perf_counter()
    gbs = int(np.shape(jax.tree_util.tree_leaves(sample_batch)[0])[0])
    dataset = ArrayDataset(_trial_dataset(sample_batch, window, seed))
    axes = plan.data_axes
    loader = DistributedDataLoader(
        dataset,
        gbs,
        mesh=plan.mesh,
        axis_name=axes[0] if len(axes) == 1 else list(axes),
    )

    def fresh_state():
        state = TrainState.create(host_params, optimizer, model_state)
        if plan.shards_parameters:
            state, _ = plan.shard_state(state)
        else:
            state = replicate(state, plan.mesh)
        return state

    # First placement banks the layout on the plan (shard_state), which
    # make_train_step(parallel=plan) requires for sharding plans — so
    # the state comes before the step.
    state0 = fresh_state()
    step = make_train_step(loss_fn, optimizer, parallel=plan)
    cp = get_compile_monitor()
    if cp is not None:
        cp.reset_run()
    _, warm = train_loop(
        step, state0, loader, epochs=1, fuse="window",
        flush_every=window, metrics=False,
    )
    if cp is not None:
        cp.reset_run()  # the timed run's retrace ledger starts clean
    _, timed = train_loop(
        step, fresh_state(), loader, epochs=epochs, fuse="window",
        flush_every=window, metrics=False,
    )
    cache = timed.get("window_cache") or {}
    retraces = len(cp.retraces) if cp is not None else None
    return {
        "examples_per_sec": round(float(timed["examples_per_sec"]), 3),
        "updates": int(timed["updates"]),
        "compile_seconds": round(
            float(warm.get("window_compile_seconds") or 0.0), 4
        ),
        "steady_compiles": int(cache.get("misses", 0)),
        "retraces": retraces,
        "seconds": round(time.perf_counter() - t0, 3),
    }


# ---------------------------------------------------------------------------
# Stage 4: bank.
# ---------------------------------------------------------------------------


def _bank_path(bank: Any) -> str | None:
    if isinstance(bank, str) and bank:
        return bank
    if bank is None:
        path = os.environ.get(BANK_ENV, "").strip()
        return path or None
    return None


def _bank_lookup(
    fingerprint: str, topo_key: str, bank: Any
) -> dict[str, Any] | None:
    rec = _BANK.get((fingerprint, topo_key))
    if rec is not None:
        return rec
    path = _bank_path(bank)
    if path and os.path.exists(path):
        try:
            with open(path, "r", encoding="utf-8") as f:
                rec = json.load(f)
        except (OSError, json.JSONDecodeError):
            return None
        if (
            isinstance(rec, dict)
            and rec.get("model_fingerprint") == fingerprint
            and _topology_key(rec.get("topology") or {}) == topo_key
            and not validate_autotune_record(rec)
        ):
            return rec
    return None


def _bank_store(record: dict[str, Any], bank: Any) -> None:
    key = (record["model_fingerprint"], _topology_key(record["topology"]))
    _BANK[key] = record
    path = _bank_path(bank)
    if path:
        try:
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(record, f, indent=1, sort_keys=True)
            os.replace(tmp, path)
        except OSError as exc:
            warnings.warn(
                f"could not write the autotune bank at {path} ({exc!r}); "
                f"the winner stays usable in-process, a later run re-tunes",
                stacklevel=2,
            )


def clear_bank() -> None:
    """Drop every in-process banked winner (test helper — file banks are
    the caller's to remove)."""
    global _LAST_RECORD
    _BANK.clear()
    _LAST_RECORD = None


def last_record() -> dict[str, Any] | None:
    """The record of this process's most recent tune (or bank reuse) —
    what the checkpoint sidecar write reads. None before any."""
    return _LAST_RECORD


def write_bank_sidecar(path: str) -> bool:
    """Write the last tune's record as ``<path>.autotune.json`` next to
    the checkpoint manifest — but only when the runtime's installed plan
    IS that tune's winner (a hand-pinned plan must not inherit another
    layout's evidence). Returns True when a sidecar was written."""
    from ..runtime import global_plan

    record = _LAST_RECORD
    if record is None:
        return False
    plan = global_plan()
    if plan is None or getattr(plan, "autotune_fingerprint", None) != (
        record["model_fingerprint"]
    ):
        return False
    target = path + ".autotune.json"
    with open(target, "w", encoding="utf-8") as f:
        json.dump(record, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    return True


# ---------------------------------------------------------------------------
# Observability: autotune.* gauges + the AUTOTUNE /status board.
# ---------------------------------------------------------------------------


def _post_observability(record: dict[str, Any], from_bank: bool) -> None:
    from ..telemetry import get_registry
    from ..telemetry import export as _export

    pruned: dict[str, int] = {reason: 0 for reason in AUTOTUNE_PRUNE_REASONS}
    best = None
    for cand in record["candidates"]:
        if cand["pruned"] in pruned:
            pruned[cand["pruned"]] += 1
        trial = cand.get("trial")
        if trial and (best is None or trial["examples_per_sec"] > best):
            best = trial["examples_per_sec"]
    trial_seconds = sum(
        (c.get("trial") or {}).get("seconds") or 0.0
        for c in record["candidates"]
    )
    registry = get_registry()
    registry.gauge("autotune.candidates_total").set(
        float(len(record["candidates"]))
    )
    for reason, count in pruned.items():
        registry.gauge("autotune.pruned", reason=reason).set(float(count))
    registry.gauge("autotune.trials").set(float(record["trials"]))
    registry.gauge("autotune.trial_seconds").set(float(trial_seconds))
    if from_bank:
        registry.counter("autotune.bank_hits").inc()
    exporter = _export.get_exporter()
    if exporter is not None and exporter.enabled:
        exporter.note_autotune(
            fingerprint=record["model_fingerprint"],
            winner=dict(record["winner"]["axes"]),
            candidates=len(record["candidates"]),
            pruned_memory=pruned.get("memory", 0),
            pruned_dominated=pruned.get("dominated", 0),
            trials=record["trials"],
            best_examples_per_sec=best,
            bank="hit" if from_bank else "tuned",
        )


# ---------------------------------------------------------------------------
# The entry point.
# ---------------------------------------------------------------------------


def _plan_from_record(
    record: dict[str, Any], devices: Sequence[jax.Device]
) -> ResolvedPlan:
    axes = {
        axis: int(size)
        for axis, size in record["winner"]["axes"].items()
        if axis in ("dp", "fsdp", "tp")
    }
    plan = ParallelConfig(
        **axes, fsdp_min_size=int(record["fsdp_min_size"])
    ).resolve(list(devices))
    plan.autotune_fingerprint = record["model_fingerprint"]
    return plan


def _trials_budget(trials: int | None) -> int:
    if trials is not None:
        return max(1, int(trials))
    raw = os.environ.get(TRIALS_ENV, "").strip()
    if raw:
        try:
            return max(1, int(raw))
        except ValueError:
            warnings.warn(
                f"ignoring {TRIALS_ENV}={raw!r} (not an int); using the "
                f"default {_DEFAULT_TRIALS}",
                stacklevel=3,
            )
    return _DEFAULT_TRIALS


def autotune(
    loss_fn: Any,
    optimizer: Any,
    params: Any,
    sample_batch: Any,
    *,
    model_state: Any = None,
    devices: Sequence[jax.Device] | None = None,
    trials: int | None = None,
    window: int = 4,
    trial_epochs: int = 2,
    fsdp_min_size: int = 1024,
    bytes_limit: int | None = None,
    bank: Any = None,
    seed: int = 0,
    force: bool = False,
) -> AutotuneResult:
    """Search the layout space for (this model, this topology) and bank
    the winner. Under ``init(parallel="auto")`` the winning plan is also
    installed as the global plan, so ``make_train_step(parallel="auto")``
    and the loader defaults pick it up with no further wiring.

    Args:
      loss_fn: the training loss ``(params, model_state, batch) ->
        (loss, new_model_state)`` — the same callable
        :func:`make_train_step` takes; trials train with it.
      optimizer: the optax transformation trials (and the static memory
        model's optimizer-state accounting) use.
      params: the model's parameter pytree (host or device arrays) —
        fingerprinted for the bank key, walked by the rule engine.
      sample_batch: one host batch (pytree of arrays, leading dim the
        GLOBAL batch size — must divide by the device count so every
        candidate shards it evenly). Trials train on ``window`` seeded
        shuffles of it; the AOT cost model lowers against its avals.
      model_state: mutable model state for ``TrainState.create``.
      devices: topology to tune for (default: the runtime mesh's
        devices when initialized, else all of ``jax.devices()``). A
        DIFFERENT device set than a banked record's re-tunes — that is
        the elastic-resume contract.
      trials: trial budget cap (default ``FLUXMPI_TPU_AUTOTUNE_TRIALS``
        or 4) — stage 2 prunes down to at most this many survivors.
      window / trial_epochs: fused-window width and timed epochs per
        trial (small on purpose — compile dominates a trial; throughput
        ranking stabilizes within a few windows).
      fsdp_min_size: forwarded to every candidate's
        :class:`ParallelConfig`.
      bytes_limit: per-device memory budget for stage 2 (default: the
        memory plane's ``bytes_limit`` stat, absent on CPU — no memory
        pruning there).
      bank: bank file path override (default ``FLUXMPI_TPU_AUTOTUNE_BANK``;
        the in-process bank always participates).
      seed: the synthetic-stream seed — fixed seed, deterministic
        candidate table and trial stream.
      force: re-tune even when the bank has a matching winner.

    Returns:
      :class:`AutotuneResult` — ``.plan`` (resolved, fingerprint-tagged),
      ``.record`` (the validated ``fluxmpi_tpu.autotune/v1`` table), and
      ``.from_bank``.
    """
    global _LAST_RECORD
    from .. import runtime as _runtime

    if devices is None:
        if _runtime.is_initialized():
            devices = list(_runtime.global_mesh().devices.flat)
        else:
            devices = jax.devices()
    devices = list(devices)
    if not devices:
        raise ValueError("autotune needs at least one device")
    lead = int(np.shape(jax.tree_util.tree_leaves(sample_batch)[0])[0])
    if lead % len(devices):
        raise ValueError(
            f"sample_batch leading dim {lead} must divide by the device "
            f"count {len(devices)} so every candidate layout shards it "
            f"evenly"
        )
    host_params = jax.device_get(params)
    fingerprint = model_fingerprint(host_params)
    topology = topology_signature(devices)
    topo_key = _topology_key(topology)

    if not force:
        banked = _bank_lookup(fingerprint, topo_key, bank)
        if banked is not None:
            plan = _plan_from_record(banked, devices)
            _LAST_RECORD = banked
            _post_observability(banked, from_bank=True)
            _runtime._install_autotuned_plan(plan)
            return AutotuneResult(plan, banked, from_bank=True)

    max_trials = _trials_budget(trials)
    candidates = enumerate_candidates(
        host_params, devices, fsdp_min_size=fsdp_min_size
    )
    if not candidates:
        raise RuntimeError(
            f"autotune found no valid layout for {len(devices)} device(s) "
            f"— the Megatron tp table matched nothing it can divide and "
            f"fsdp_min_size={fsdp_min_size} left nothing to shard; pin a "
            f"ParallelConfig by hand"
        )

    # Stage 2a: the static memory model, against the memory plane's
    # per-device budget when one is reported (CPU reports none).
    template = state_template(host_params, optimizer, model_state)
    if bytes_limit is None:
        from ..telemetry.memory import device_memory_stats

        stats = device_memory_stats(devices[0])
        limit = stats.get("bytes_limit")
        bytes_limit = int(limit) if limit else None
    for cand in candidates:
        cand.mem_bytes_per_device = layout_bytes(template, cand.plan)

    # Stage 2b: the AOT cost score — only for memory-feasible layouts
    # (lowering a layout the budget already killed is wasted compile).
    for cand in candidates:
        if bytes_limit and cand.mem_bytes_per_device > bytes_limit:
            continue
        cost = _static_cost(
            loss_fn, optimizer, template, sample_batch, cand.plan
        )
        if cost:
            cand.flops = cost.get("flops")
            cand.bytes_accessed = cost.get("bytes_accessed")
        cand.score = _score(cost)

    survivors = _prune(
        candidates, bytes_limit=bytes_limit, max_trials=max_trials
    )
    if not survivors:
        raise RuntimeError(
            f"every candidate layout exceeds the {bytes_limit}-byte "
            f"per-device budget — this model does not fit this topology "
            f"under dp×fsdp×tp alone (add pp by hand, or more devices)"
        )

    # Stage 3: fused-window trials on the real train_loop machinery.
    for cand in survivors:
        cand.trial = _run_trial(
            loss_fn, optimizer, host_params, model_state, sample_batch,
            cand.plan, window=window, epochs=trial_epochs, seed=seed,
        )
    winner = max(
        survivors,
        key=lambda c: (
            c.trial["examples_per_sec"],
            -(c.score or 0.0),
        ),
    )

    record = {
        "schema": AUTOTUNE_SCHEMA,
        "time_unix": time.time(),
        "model_fingerprint": fingerprint,
        "topology": topology,
        "fsdp_min_size": int(fsdp_min_size),
        "winner": {
            "axes": dict(winner.axes),
            "axis_names": dict(winner.plan.axis_names),
        },
        "trials": len(survivors),
        "candidates": [c.describe() for c in candidates],
    }
    errors = validate_autotune_record(record)
    if errors:  # pragma: no cover - producer drift guard
        raise ValueError(
            "autotune produced an invalid record: " + "; ".join(errors)
        )
    _bank_store(record, bank)
    _LAST_RECORD = record
    winner.plan.autotune_fingerprint = fingerprint
    _post_observability(record, from_bank=False)
    _runtime._install_autotuned_plan(winner.plan)
    return AutotuneResult(winner.plan, record, from_bank=False)
