"""Ring attention — sequence/context parallelism over a mesh axis.

Long-context scaling: the sequence dimension is sharded over a mesh axis
(``sp``), each device holds one block of Q/K/V, and K/V blocks rotate around
the ring via ``lax.ppermute`` (ICI neighbor hops) while each device
accumulates its queries' attention with a numerically-stable online softmax
(blockwise/flash accumulation). Peak memory is O(seq/n_devices) per device
and the K/V transfer overlaps with the block computation, which is exactly
the layout the TPU torus wants.

Two causal schedules:

- **contiguous** (:func:`ring_attention` with ``causal=True``): device i
  owns sequence block i. Simple, but causally imbalanced — device 0 skips
  all but one ring tick while device n-1 computes on every tick.
- **zigzag** (:func:`zigzag_ring_attention`): the sequence is split into
  ``2n`` chunks and device i owns chunks ``(i, 2n-1-i)``. Every device then
  does exactly the same causal work on every tick (two half-size block
  attends, or two diagonals plus one full on tick 0), recovering the
  ~2× causal FLOP saving that the contiguous schedule wastes as idle slots.
  Use :func:`zigzag_indices` to permute global arrays into this layout.

Masking beyond ``causal`` uses the same integer segment-id convention as
:mod:`fluxmpi_tpu.ops.flash_attention` (attend iff ids equal and key id
nonzero; 0 = padding); K/V segment ids rotate around the ring with their
blocks.

The reference framework never touches the sequence dimension (SURVEY.md §5
— DP-only); this module is the capability extension that makes long-context
training first-class on TPU, designed so the ``sp`` axis composes with the
``dp`` axis in one mesh (e.g. ``{"dp": 4, "sp": 2}``).

Shapes: ``q, k, v`` are ``(batch, seq_local, heads, head_dim)`` inside a
``shard_map`` whose in_specs shard the global sequence over ``axis_name``.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ._compat import axis_size, shard_map_unchecked
from .plan import plan_axis_name

__all__ = [
    "ring_attention",
    "zigzag_ring_attention",
    "zigzag_indices",
    "make_ring_attention",
    "ring_attention_fn",
]

_NEG_INF = -1e30


def _expand_kv(t, h):
    """Repeat grouped K/V heads up to the query head count for the dense
    math paths. The flash kernel reads grouped heads natively (its
    ``_kv_row`` index map); only the dense fallback materializes the
    repeat — and only *locally*, after any ring rotation, so the ICI hops
    still move the small ``h_kv`` blocks (the point of GQA on the ring)."""
    h_kv = t.shape[2]
    if h_kv == h:
        return t
    if h % h_kv:
        raise ValueError(
            f"query head count {h} must be a multiple of the kv head "
            f"count {h_kv} (grouped-query attention)"
        )
    return jnp.repeat(t, h // h_kv, axis=2)


def _block_attend(q, k, v, o, m, l, mask):
    """One blockwise online-softmax update.

    q: [b, sq, h, d]; k/v: [b, sk, h, d]; o: [b, sq, h, d];
    m/l: [b, sq, h]; mask: bool broadcastable to [b, h, sq, sk]
    (True = attend) or None.
    """
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    # scores: [b, h, sq, sk] — contraction on head_dim, batched on (b, h)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if mask is not None:
        scores = jnp.where(mask, scores, _NEG_INF)
    m_block = jnp.max(scores, axis=-1)  # [b, h, sq]
    m_block = jnp.moveaxis(m_block, 1, -1)  # [b, sq, h]
    m_new = jnp.maximum(m, m_block)
    # renormalize previous accumulators
    alpha = jnp.exp(m - m_new)  # [b, sq, h]
    p = jnp.exp(scores - jnp.moveaxis(m_new, -1, 1)[:, :, :, None])  # [b,h,sq,sk]
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    l_new = l * alpha + jnp.moveaxis(jnp.sum(p, axis=-1), 1, -1)
    o_new = o * alpha[..., None] + jnp.moveaxis(
        jnp.einsum("bhqk,bkhd->bhqd", p, v), 1, 2
    )
    return o_new, m_new, l_new


def _seg_mask4(qseg, kseg):
    """Segment mask broadcastable to [b, h, sq, sk]: attend iff same segment
    and the key is not padding (id 0)."""
    q4 = qseg[:, None, :, None]
    k4 = kseg[:, None, None, :]
    return (q4 == k4) & (k4 != 0)


def _dense_with_lse(q, k, v, causal, qseg=None, kseg=None):
    """Dense local attend returning (normalized out [b,sq,h,d] f32,
    lse [b,h,sq] f32) — the non-Pallas twin of flash_attention_with_lse,
    used by the zigzag schedule's CPU/debug path. Handles grouped K/V
    heads (repeated locally) and optional segment ids."""
    k = _expand_kv(k, q.shape[2])
    v = _expand_kv(v, q.shape[2])
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum(
        "bqhd,bkhd->bhqk",
        q.astype(jnp.float32),
        k.astype(jnp.float32),
    ) * scale
    mask = None
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        mask = (
            jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        )[None, None]
    if qseg is not None:
        smask = _seg_mask4(qseg, kseg)
        mask = smask if mask is None else jnp.logical_and(mask, smask)
    if mask is not None:
        s = jnp.where(mask, s, _NEG_INF)
    m = jnp.max(s, axis=-1)  # [b, h, sq]
    p = jnp.exp(s - m[..., None])
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    lse = m + jnp.log(l_safe)
    o = jnp.einsum(
        "bhqk,bkhd->bqhd", p / l_safe[..., None], v.astype(jnp.float32)
    )
    return o, lse


def _lse_merge(o, lse, o_blk, lse_blk):
    """Merge an accumulated (o [b,sq,h,d] f32, lse [b,sq,h]) with a new
    normalized block result whose lse arrives as [b, h, sq] (the kernel
    convention)."""
    lse_blk = jnp.moveaxis(lse_blk, 1, -1)
    lse_new = jnp.logaddexp(lse, lse_blk)
    w_prev = jnp.exp(lse - lse_new)[..., None]
    w_blk = jnp.exp(lse_blk - lse_new)[..., None]
    return o * w_prev + o_blk.astype(jnp.float32) * w_blk, lse_new


def _fold_seed(seed, *salts):
    """Per-call-site dropout seed: fold traced/static salts (device index,
    ring tick, attend id) into the base seed so every block attend draws an
    independent mask stream — the kernels mix further, so simple odd-
    constant multiplies suffice here."""
    s = jnp.asarray(seed, jnp.uint32)
    consts = (0x9E3779B1, 0x85EBCA6B, 0xC2B2AE35, 0x27D4EB2F)
    for salt, c in zip(salts, consts):
        s = s + (jnp.asarray(salt).astype(jnp.uint32) + 1) * jnp.uint32(c)
    return s


def _ring_flash(
    q, k, v, *, name: str, causal: bool, n: int, idx, qseg, kseg,
    block_q: int | None, block_k: int | None, window: int | None = None,
    dropout_rate: float = 0.0, dropout_seed=None,
):
    """Ring accumulation with the Pallas flash kernel as the local block
    attend (:func:`fluxmpi_tpu.ops.flash_attention_with_lse`).

    Each resident K/V block is attended by the flash kernel, which returns a
    *normalized* block output plus its logsumexp; blocks merge in plain JAX
    via the standard lse-weighted combine. The kernel's custom VJP honors
    the lse cotangent, so the whole ring differentiates exactly.

    Attention dropout composes exactly with the merge: the kernel
    accumulates softmax normalization from UNdropped probabilities, so the
    lse-weighted combine of dropped block outputs equals global
    post-softmax dropout. Each (device, tick) attend folds its coordinates
    into the seed — independent masks per resident block.

    ``window`` (requires ``causal``, enforced by the caller): on tick
    ``s``, every LIVE resident block sits exactly ``s`` ring positions in
    the past, so its global displacement is the STATIC ``s·sq`` — the
    diagonal tick runs the kernel's normal causal+window mask, and each
    past tick runs the band-only mask ``q - k < window - s·sq`` (the
    causal floor holds globally). The tick loop unrolls over the
    ``ceil``-few ticks whose band is alive and stops rotating afterwards:
    compute AND communication are O(window), not O(seq) (VERDICT r4
    next #8 — this replaces the old ValueError).
    """
    from ..ops.flash_attention import flash_attention_with_lse

    b, sq, h, d = q.shape
    o = jnp.zeros((b, sq, h, d), dtype=jnp.float32)
    lse = jnp.full((b, sq, h), _NEG_INF, dtype=jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]
    has_seg = qseg is not None

    def attend(k_blk, v_blk, kseg_blk, local_causal, src, local_window=None):
        seg = (qseg, kseg_blk) if has_seg else None
        seed = (
            _fold_seed(dropout_seed, idx, src) if dropout_rate else None
        )
        return flash_attention_with_lse(
            q, k_blk, v_blk, causal=local_causal, segment_ids=seg,
            window=local_window, block_q=block_q, block_k=block_k,
            dropout_rate=dropout_rate, dropout_seed=seed,
        )

    if window is not None:
        # Windowed schedule: static tick loop (see docstring). sq == sk
        # on a causal self-attention ring (equal sequence shards).
        sk = k.shape[1]
        if sk != sq:
            raise ValueError(
                f"windowed ring attention requires equal q/kv shards, got "
                f"{sq} vs {sk}"
            )
        o, lse = _lse_merge(
            o, lse, *attend(k, v, kseg if has_seg else None, True, idx, window)
        )
        k_blk, v_blk, kseg_blk = k, v, (kseg if has_seg else None)
        empty = (
            jnp.zeros((b, sq, h, d), q.dtype),
            jnp.full((b, h, sq), _NEG_INF, jnp.float32),
        )
        for s in range(1, n):
            w_local = window - s * sq
            # Band dead for every device from this tick on: the closest
            # pair (local q=0 vs k=sq-1, displacement s·sq - (sq-1)) is
            # already outside the window. Stop attending AND rotating.
            if w_local <= 1 - sq:
                break
            # Band all-true (farthest pair, local q=sq-1 vs k=0, has
            # displacement s·sq + (sq-1) < window ⇔ w_local ≥ sq): drop
            # the window so every such tick shares ONE unmasked kernel
            # specialization instead of compiling a distinct fwd/dq/dkv
            # trio per static w_local.
            tick_window = None if w_local >= sq else w_local
            k_blk = jax.lax.ppermute(k_blk, name, perm)
            v_blk = jax.lax.ppermute(v_blk, name, perm)
            if has_seg:
                kseg_blk = jax.lax.ppermute(kseg_blk, name, perm)
            o_blk, lse_blk = jax.lax.cond(
                idx >= s,
                lambda _, _s=s, _w=tick_window: attend(
                    k_blk, v_blk, kseg_blk, False, idx - _s, _w
                ),
                lambda _: empty,
                None,
            )
            o, lse = _lse_merge(o, lse, o_blk, lse_blk)
        return o.astype(q.dtype)

    def body(s, carry):
        o, lse, k_blk, v_blk, kseg_blk = carry
        # After s rotations, the resident block originated on ring position
        # (idx - s) mod n.
        src = (idx - s) % n

        def full_blk(_):
            return attend(k_blk, v_blk, kseg_blk, False, src)

        if causal:
            def diag_blk(_):
                # Same ring position: global offsets cancel, local causal.
                return attend(k_blk, v_blk, kseg_blk, True, src)

            def skip_blk(_):
                return (
                    jnp.zeros((b, sq, h, d), q.dtype),
                    jnp.full((b, h, sq), _NEG_INF, jnp.float32),
                )

            o_blk, lse_blk = jax.lax.cond(
                src > idx,
                skip_blk,
                lambda _: jax.lax.cond(src == idx, diag_blk, full_blk, None),
                None,
            )
        else:
            o_blk, lse_blk = full_blk(None)

        o2, lse2 = _lse_merge(o, lse, o_blk, lse_blk)
        k_next = jax.lax.ppermute(k_blk, name, perm)
        v_next = jax.lax.ppermute(v_blk, name, perm)
        kseg_next = (
            jax.lax.ppermute(kseg_blk, name, perm) if has_seg else kseg_blk
        )
        return o2, lse2, k_next, v_next, kseg_next

    kseg0 = kseg if has_seg else jnp.zeros((), jnp.int32)
    o, lse, _, _, _ = jax.lax.fori_loop(0, n, body, (o, lse, k, v, kseg0))
    return o.astype(q.dtype)


def _local_attend(
    q, k, v, *, causal, segment_ids=None, use_flash=False,
    block_q=None, block_k=None, window=None,
    dropout_rate=0.0, dropout_seed=None,
):
    """Single-device attention with ring semantics — the n=1 ring. Used as
    the unbound-axis fallback so ring/zigzag models initialize and run
    outside ``shard_map`` without a dense twin, and as the local attend of
    :func:`fluxmpi_tpu.parallel.ulysses.ulysses_attention` (where positions
    are global, so the flash kernel's ``window`` applies directly)."""
    if use_flash:
        from ..ops.flash_attention import flash_attention

        return flash_attention(
            q, k, v, causal=causal, segment_ids=segment_ids,
            block_q=block_q, block_k=block_k, window=window,
            dropout_rate=dropout_rate, dropout_seed=dropout_seed,
        )
    if dropout_rate:
        raise ValueError(
            "attention dropout on the SP layers requires use_flash=True "
            "(the in-kernel mask; the dense debug paths do not implement it)"
        )
    qseg, kseg = _normalize_ring_segments(
        segment_ids, q.shape[0], q.shape[1], k.shape[1]
    )
    k = _expand_kv(k, q.shape[2])
    v = _expand_kv(v, q.shape[2])
    mask = None
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        pos = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        if window is not None:
            pos = pos & (
                jnp.arange(sq)[:, None] - jnp.arange(sk)[None, :] < window
            )
        mask = pos[None, None]
    if qseg is not None:
        smask = _seg_mask4(qseg, kseg)
        mask = smask if mask is None else jnp.logical_and(mask, smask)
    o = jnp.zeros_like(q, dtype=jnp.float32)
    m = jnp.full((*q.shape[:2], q.shape[2]), _NEG_INF, jnp.float32)
    l = jnp.zeros_like(m)
    o, m, l = _block_attend(
        q.astype(jnp.float32), k.astype(jnp.float32),
        v.astype(jnp.float32), o, m, l, mask
    )
    l = jnp.where(l == 0.0, 1.0, l)
    return (o / l[..., None]).astype(q.dtype)


def _normalize_ring_segments(segment_ids, b, sq, sk):
    """Ring spelling of the flash kernel's segment normalization — shapes
    are the *local shards* ``(batch, seq_local)``; validation is shared
    with :mod:`fluxmpi_tpu.ops.flash_attention` so the two paths cannot
    drift."""
    from ..ops.flash_attention import _normalize_segments

    return _normalize_segments(segment_ids, b, sq, sk)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis_name: str | None = None,
    causal: bool = False,
    segment_ids=None,
    use_flash: bool = False,
    block_q: int | None = None,
    block_k: int | None = None,
    window: int | None = None,
    dropout_rate: float = 0.0,
    dropout_seed=None,
) -> jnp.ndarray:
    """Blockwise ring attention; call inside ``shard_map`` with the sequence
    dimension of q/k/v sharded over ``axis_name``.

    Each of the ``n`` ring steps attends local queries to the K/V block
    currently resident, then rotates K/V to the next ring neighbor. With
    ``causal=True``, blocks strictly in the future are skipped via a zero
    mask (compiled as a select — no dynamic control flow); for balanced
    causal work use :func:`zigzag_ring_attention` instead.

    ``segment_ids``: optional int32 local shards ``[batch, seq_local]`` (or
    a ``(q_seg, kv_seg)`` pair) in the flash-kernel convention — attend iff
    ids equal and key id nonzero, 0 = padding. K/V ids rotate with their
    blocks.

    ``use_flash=True`` swaps the dense local block attend for the Pallas
    flash kernel (memory-optimal on-chip: the [sq, sk] score block never
    leaves VMEM); local sequence lengths must then divide ``block_q`` /
    ``block_k`` (both threaded to the kernel — tune for shards smaller
    than 128).

    ``window`` (sliding-window / local attention, requires ``causal=True``)
    is honored on both paths. The dense ring masks on global positions.
    The flash ring exploits that a live resident block on tick ``s`` is
    always exactly ``s`` ring positions in the past — a STATIC global
    displacement — so the diagonal tick uses the kernel's causal+window
    mask and each past tick the band-only mask ``q-k < window - s·sq``
    (:func:`fluxmpi_tpu.ops.flash_attention_with_lse` with
    ``causal=False``); ticks whose band is dead are never attended NOR
    rotated, making compute and ICI traffic O(window) instead of O(seq).
    """
    if window is not None and not causal:
        raise ValueError("window (sliding-window attention) requires causal=True")
    if dropout_rate and not use_flash:
        raise ValueError(
            "ring_attention dropout requires use_flash=True (in-kernel "
            "position-hash masks; see flash_attention dropout_rate)"
        )
    if dropout_rate and dropout_seed is None:
        raise ValueError(
            "dropout_rate > 0 requires dropout_seed (an int or traced "
            "uint32 scalar)"
        )
    name = axis_name or plan_axis_name("sp")
    try:
        n = axis_size(name)
    except NameError:
        # Unbound axis: not inside a shard_map binding `name` — e.g.
        # ``module.init`` on a ring-attention model outside the mapped
        # region (VERDICT r2 weak #6: the old behavior was an opaque raise
        # and a documented "init a dense twin" workaround). A one-device
        # ring is just local attention, so compute exactly that.
        return _local_attend(
            q, k, v, causal=causal, segment_ids=segment_ids,
            use_flash=use_flash, block_q=block_q, block_k=block_k,
            window=window,
            dropout_rate=dropout_rate, dropout_seed=dropout_seed,
        )
    idx = jax.lax.axis_index(name)
    b, sq, h, d = q.shape
    qseg, kseg = _normalize_ring_segments(segment_ids, b, sq, k.shape[1])

    if use_flash:
        return _ring_flash(
            q, k, v, name=name, causal=causal, n=n, idx=idx,
            qseg=qseg, kseg=kseg, block_q=block_q, block_k=block_k,
            window=window,
            dropout_rate=dropout_rate, dropout_seed=dropout_seed,
        )

    o = jnp.zeros_like(q, dtype=jnp.float32)
    m = jnp.full((b, sq, h), _NEG_INF, dtype=jnp.float32)
    l = jnp.zeros((b, sq, h), dtype=jnp.float32)

    qf = q.astype(jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]
    has_seg = qseg is not None

    def body(s, carry):
        o, m, l, k_blk, v_blk, kseg_blk = carry
        # After s rotations, the resident block originated on ring position
        # (idx - s) mod n.
        src = (idx - s) % n
        sk_blk = k_blk.shape[1]

        def attend_block(carry):
            o, m, l = carry
            # GQA: the rotating blocks keep their h_kv heads (small ICI
            # hops); the repeat to h query heads happens locally,
            # post-rotation.
            kf = _expand_kv(k_blk, h).astype(jnp.float32)
            vf = _expand_kv(v_blk, h).astype(jnp.float32)
            mask = None
            if causal:
                q_pos = idx * sq + jnp.arange(sq)
                k_pos = src * sk_blk + jnp.arange(sk_blk)
                pos = q_pos[:, None] >= k_pos[None, :]
                if window is not None:
                    pos = jnp.logical_and(
                        pos, q_pos[:, None] - k_pos[None, :] < window
                    )
                mask = pos[None, None]
            if has_seg:
                smask = _seg_mask4(qseg, kseg_blk)
                mask = smask if mask is None else jnp.logical_and(mask, smask)
            return _block_attend(qf, kf, vf, o, m, l, mask)

        if causal:
            # Skip ticks whose resident block is entirely masked — strictly
            # in the future (the contiguous causal imbalance) or wholly
            # outside the window band — the dense twin of _ring_flash's
            # cond skip: the K/V still rotates, the compute doesn't run.
            live = (idx + 1) * sq - 1 >= src * sk_blk
            if window is not None:
                live = jnp.logical_and(
                    live, idx * sq - ((src + 1) * sk_blk - 1) < window
                )
            o2, m2, l2 = jax.lax.cond(
                live, attend_block, lambda c: c, (o, m, l)
            )
        else:
            o2, m2, l2 = attend_block((o, m, l))
        k_next = jax.lax.ppermute(k_blk, name, perm)
        v_next = jax.lax.ppermute(v_blk, name, perm)
        kseg_next = (
            jax.lax.ppermute(kseg_blk, name, perm) if has_seg else kseg_blk
        )
        return o2, m2, l2, k_next, v_next, kseg_next

    kseg0 = kseg if has_seg else jnp.zeros((), jnp.int32)
    o, m, l, _, _, _ = jax.lax.fori_loop(0, n, body, (o, m, l, k, v, kseg0))
    # Guard fully-masked rows (l == 0) against 0/0.
    l = jnp.where(l == 0.0, 1.0, l)
    return (o / l[..., None]).astype(q.dtype)


def zigzag_tick_work(i: int, s: int, n: int) -> tuple[tuple[str, str, str], ...]:
    """The zigzag schedule as data: the chunk attends device ``i`` performs
    on tick ``s`` of an ``n``-device ring, as ``(q_chunk, kv_chunk, kind)``
    triples with chunks named ``"lo"``/``"hi"`` and kind ``"full"`` or
    ``"diag"`` (diag ≈ half the FLOPs of full). This is the single source of
    truth the implementation mirrors (tick 0 literally; ticks ≥ 1 via the
    src</> predicates) and the balance test audits."""
    src = (i - s) % n
    if s == 0:
        return (("lo", "lo", "diag"), ("hi", "lo", "full"), ("hi", "hi", "diag"))
    if src < i:
        return (("hi", "lo", "full"), ("lo", "lo", "full"))
    return (("hi", "lo", "full"), ("hi", "hi", "full"))


def zigzag_indices(seq_len: int, n: int) -> np.ndarray:
    """Permutation taking a contiguous global sequence to zigzag layout:
    split into ``2n`` chunks, device i owns chunks ``(i, 2n-1-i)``. Apply as
    ``x[:, zigzag_indices(s, n)]`` before the sharded call; invert with
    ``jnp.argsort`` of the same indices."""
    if seq_len % (2 * n):
        raise ValueError(
            f"sequence length {seq_len} must be divisible by 2·n = {2 * n}"
        )
    c = seq_len // (2 * n)
    chunks = np.arange(seq_len).reshape(2 * n, c)
    order = []
    for i in range(n):
        order += [i, 2 * n - 1 - i]
    return chunks[order].reshape(-1)


def zigzag_ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis_name: str | None = None,
    segment_ids=None,
    use_flash: bool = False,
    block_q: int | None = None,
    block_k: int | None = None,
    dropout_rate: float = 0.0,
    dropout_seed=None,
) -> jnp.ndarray:
    """Causal ring attention with the zigzag-balanced schedule; call inside
    ``shard_map`` on arrays pre-permuted with :func:`zigzag_indices`.

    Device i holds global chunks ``(i, 2n-1-i)`` concatenated along the
    sequence axis. Per ring tick every device performs exactly two
    half-block attends (tick 0: two causal diagonals plus one full), so no
    device ever idles — unlike the contiguous causal schedule where device 0
    skips n-1 of its n ticks. Total work is the causal ideal, half of the
    non-causal ring. Schedule spec: :func:`zigzag_tick_work`.

    ``segment_ids``: optional int32 local shards ``[batch, seq_local]`` in
    the flash-kernel convention (attend iff ids equal, key id 0 = padding),
    **pre-permuted with the same** :func:`zigzag_indices` **as q/k/v** so
    each id rides with its token. They split into the same (lo, hi) chunks
    as Q and rotate around the ring with their K/V blocks, so packed and
    padded batches get the balanced causal schedule too
    (:func:`make_ring_attention` with ``schedule="zigzag"`` does the
    permutation for you).

    Grouped-query attention: K/V may carry fewer heads than Q; the rotating
    blocks stay at ``h_kv`` heads (smaller ICI hops) and the flash kernel
    reads them natively.
    """
    from ..ops.flash_attention import flash_attention_with_lse

    if dropout_rate and not use_flash:
        raise ValueError(
            "zigzag_ring_attention dropout requires use_flash=True "
            "(in-kernel position-hash masks)"
        )
    if dropout_rate and dropout_seed is None:
        raise ValueError(
            "dropout_rate > 0 requires dropout_seed (an int or traced "
            "uint32 scalar)"
        )
    name = axis_name or plan_axis_name("sp")
    try:
        n = axis_size(name)
    except NameError:
        # Unbound axis (module.init outside shard_map): n=1 zigzag layout
        # is the identity permutation, so plain causal attention is exact.
        return _local_attend(
            q, k, v, causal=True, segment_ids=segment_ids,
            use_flash=use_flash, block_q=block_q, block_k=block_k,
            dropout_rate=dropout_rate, dropout_seed=dropout_seed,
        )
    idx = jax.lax.axis_index(name)
    b, sq, h, d = q.shape
    if sq % 2:
        raise ValueError(f"local sequence length {sq} must be even (2 chunks)")
    c = sq // 2
    qseg, kseg = _normalize_ring_segments(segment_ids, b, sq, k.shape[1])
    has_seg = qseg is not None

    def attend(qc, kc, vc, local_causal, qs=None, ks=None, attend_id=0):
        seg = (qs, ks) if qs is not None else None
        if use_flash:
            seed = (
                _fold_seed(dropout_seed, idx, attend_id)
                if dropout_rate else None
            )
            return flash_attention_with_lse(
                qc, kc, vc, causal=local_causal, segment_ids=seg,
                block_q=None if block_q is None else min(block_q, c),
                block_k=None if block_k is None else min(block_k, c),
                dropout_rate=dropout_rate, dropout_seed=seed,
            )
        return _dense_with_lse(qc, kc, vc, local_causal, qs, ks)

    def split(t):
        return t[:, :c], t[:, c:]

    q_lo, q_hi = split(q)
    qseg_lo, qseg_hi = split(qseg) if has_seg else (None, None)

    o_lo = jnp.zeros((b, c, h, d), jnp.float32)
    o_hi = jnp.zeros((b, c, h, d), jnp.float32)
    lse_lo = jnp.full((b, c, h), _NEG_INF, jnp.float32)
    lse_hi = jnp.full((b, c, h), _NEG_INF, jnp.float32)

    # Tick 0 — resident KV is our own pair: zigzag_tick_work(i, 0, n).
    kv_lo_k, kv_hi_k = split(k)
    kv_lo_v, kv_hi_v = split(v)
    ks_lo, ks_hi = split(kseg) if has_seg else (None, None)
    o_blk, lse_blk = attend(
        q_lo, kv_lo_k, kv_lo_v, True, qseg_lo, ks_lo, attend_id=0
    )  # (lo, lo, diag)
    o_lo, lse_lo = _lse_merge(o_lo, lse_lo, o_blk, lse_blk)
    o_blk, lse_blk = attend(
        q_hi, kv_lo_k, kv_lo_v, False, qseg_hi, ks_lo, attend_id=1
    )  # (hi, lo, full)
    o_hi, lse_hi = _lse_merge(o_hi, lse_hi, o_blk, lse_blk)
    o_blk, lse_blk = attend(
        q_hi, kv_hi_k, kv_hi_v, True, qseg_hi, ks_hi, attend_id=2
    )  # (hi, hi, diag)
    o_hi, lse_hi = _lse_merge(o_hi, lse_hi, o_blk, lse_blk)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(s, carry):
        o_lo, lse_lo, o_hi, lse_hi, k_blk, v_blk, kseg_blk = carry
        k_blk = jax.lax.ppermute(k_blk, name, perm)
        v_blk = jax.lax.ppermute(v_blk, name, perm)
        if has_seg:
            kseg_blk = jax.lax.ppermute(kseg_blk, name, perm)
        src = (idx - s) % n
        klo, khi = split(k_blk)
        vlo, vhi = split(v_blk)
        kslo, kshi = split(kseg_blk) if has_seg else (None, None)

        # Always: (hi, lo, full) — q_hi = chunk 2n-1-idx is in the future of
        # every lo chunk src < n.
        o_blk, lse_blk = attend(q_hi, klo, vlo, False, qseg_hi, kslo,
                                attend_id=1 + 2 * s)
        o_hi, lse_hi = _lse_merge(o_hi, lse_hi, o_blk, lse_blk)

        # Predicate-selected second attend: src < idx → (lo, lo, full);
        # src > idx → (hi, hi, full). Operands and target slot switch
        # together; cost is identical on both sides so every device does the
        # same work per tick (zigzag_tick_work).
        pred = src < idx
        q_sel = jnp.where(pred, q_lo, q_hi)
        k_sel = jnp.where(pred, klo, khi)
        v_sel = jnp.where(pred, vlo, vhi)
        qs_sel = jnp.where(pred, qseg_lo, qseg_hi) if has_seg else None
        ks_sel = jnp.where(pred, kslo, kshi) if has_seg else None
        o_blk, lse_blk = attend(q_sel, k_sel, v_sel, False, qs_sel, ks_sel,
                                attend_id=2 + 2 * s)
        new_lo = _lse_merge(o_lo, lse_lo, o_blk, lse_blk)
        new_hi = _lse_merge(o_hi, lse_hi, o_blk, lse_blk)
        o_lo = jnp.where(pred, new_lo[0], o_lo)
        lse_lo = jnp.where(pred, new_lo[1], lse_lo)
        o_hi = jnp.where(pred, o_hi, new_hi[0])
        lse_hi = jnp.where(pred, lse_hi, new_hi[1])
        return o_lo, lse_lo, o_hi, lse_hi, k_blk, v_blk, kseg_blk

    kseg0 = kseg if has_seg else jnp.zeros((), jnp.int32)
    o_lo, lse_lo, o_hi, lse_hi, _, _, _ = jax.lax.fori_loop(
        1, n, body, (o_lo, lse_lo, o_hi, lse_hi, k, v, kseg0)
    )
    return jnp.concatenate([o_lo, o_hi], axis=1).astype(q.dtype)


def _adapter_dropout(kwargs):
    """Flax-adapter dropout plumbing shared by the SP ``attention_fn``
    wrappers: read the module-passed dropout kwargs and derive an
    in-kernel (rate, traced seed) pair — zero when eval/deterministic."""
    rate = float(kwargs.get("dropout_rate", 0.0))
    if not rate or kwargs.get("deterministic", True):
        return 0.0, None
    rng = kwargs.get("dropout_rng")
    if rng is None:
        raise ValueError(
            "dropout_rate > 0 with deterministic=False requires a "
            "dropout_rng (flax passes it when the module is given a "
            "'dropout' rng collection)"
        )
    return rate, jax.random.bits(rng, (), jnp.uint32)


def ring_attention_fn(
    axis_name: str | None = None,
    causal: bool = False,
    use_flash: bool = False,
    block_q: int | None = None,
    block_k: int | None = None,
    window: int | None = None,
):
    """An ``attention_fn`` drop-in for ``nn.MultiHeadDotProductAttention``.

    Use on a :class:`fluxmpi_tpu.models.TransformerEncoder` applied inside a
    ``shard_map`` whose in_specs shard the sequence over ``axis_name`` —
    every other encoder op (LayerNorm, MLP, residuals) is pointwise over the
    sequence, so only attention needs the ring. Explicit masks are not
    supported (use ``causal=True`` for causal masking; the mask is derived
    from global ring positions). ``block_q``/``block_k`` thread to the flash
    kernel — set them to divisors of the local sequence shard when it is
    smaller than 128.

    Attention dropout (``dropout_rate > 0`` on the flax module, training
    mode) runs in-kernel on the flash path, seeded from the module's
    dropout rng (requires ``use_flash=True``). The in-kernel masks are
    independent per (batch, head): flax's ``broadcast_dropout=True``
    default (one mask shared across batch and heads) is NOT honored on
    this path — same caveat as
    :func:`fluxmpi_tpu.ops.flash_attention_fn`'s kernel impl. Use a dense
    single-device attention if broadcast regularization semantics matter.

    ``module.init`` works outside the ``shard_map`` too: with no bound
    ``sp`` axis the ring degrades to exact single-device attention (the
    n=1 ring), so parameters initialize without a dense twin.
    """
    def fn(query, key, value, bias=None, mask=None, **kwargs):
        if bias is not None or mask is not None:
            raise ValueError(
                "ring_attention_fn derives masking from ring position; "
                "pass causal=True instead of an explicit mask/bias"
            )
        rate, seed = _adapter_dropout(kwargs)
        return ring_attention(
            query, key, value, axis_name=axis_name, causal=causal,
            use_flash=use_flash, block_q=block_q, block_k=block_k,
            window=window, dropout_rate=rate, dropout_seed=seed,
        )

    return fn


def make_ring_attention(
    mesh: Mesh | None = None,
    *,
    axis_name: str | None = None,
    causal: bool = False,
    batch_axis_name: str | None = None,
    use_flash: bool = False,
    schedule: str = "contiguous",
    block_q: int | None = None,
    block_k: int | None = None,
    window: int | None = None,
    dropout_rate: float = 0.0,
):
    """Wrap :func:`ring_attention` for eager use on mesh-sharded arrays.

    Returns ``fn(q, k, v) -> out`` where the inputs' sequence dimension
    (axis 1) is laid out over ``axis_name`` (and optionally batch over
    ``batch_axis_name``). Compiled once per shape.

    ``schedule="zigzag"`` (causal only) applies the :func:`zigzag_indices`
    permutation on the way in and its inverse on the way out, so callers
    keep contiguous global sequences while the devices run the balanced
    schedule.
    """
    from ..runtime import global_mesh

    if schedule not in ("contiguous", "zigzag"):
        raise ValueError(f"unknown schedule {schedule!r}")
    if schedule == "zigzag" and not causal:
        raise ValueError("zigzag schedule only applies to causal attention")
    if schedule == "zigzag" and window is not None:
        raise ValueError(
            "window is not supported on the zigzag schedule (chunk attends "
            "carry global offsets); use schedule='contiguous', or "
            "ulysses_attention"
        )

    mesh = mesh or global_mesh()
    sp = axis_name or plan_axis_name("sp")
    dp = batch_axis_name
    spec = P(dp, sp)

    if dropout_rate and not use_flash:
        raise ValueError(
            "make_ring_attention dropout requires use_flash=True"
        )

    if schedule == "zigzag":
        def body(q, k, v, seed, *seg):
            return zigzag_ring_attention(
                q, k, v, axis_name=sp, use_flash=use_flash,
                segment_ids=seg if seg else None,
                block_q=block_q, block_k=block_k,
                dropout_rate=dropout_rate, dropout_seed=seed,
            )
    else:
        def body(q, k, v, seed, *seg):
            return ring_attention(
                q, k, v, axis_name=sp, causal=causal, use_flash=use_flash,
                segment_ids=seg if seg else None,
                block_q=block_q, block_k=block_k, window=window,
                dropout_rate=dropout_rate, dropout_seed=seed,
            )

    def body_noseed(q, k, v, *seg):
        return body(q, k, v, None, *seg)

    jitted_by_nseg: dict = {}

    def _jitted(n_seg: int):
        # One shard_map per arity: segment operands are extra sharded
        # inputs, so the mapped signature differs with/without them; the
        # dropout seed (replicated scalar) is a fourth operand only when
        # the wrapper was built with dropout_rate > 0.
        if n_seg not in jitted_by_nseg:
            if dropout_rate:
                specs = (spec, spec, spec, P()) + (spec,) * n_seg
                jitted_by_nseg[n_seg] = jax.jit(shard_map_unchecked(
                    body, mesh, in_specs=specs, out_specs=spec
                ))
            else:
                specs = (spec,) * (3 + n_seg)
                jitted_by_nseg[n_seg] = jax.jit(shard_map_unchecked(
                    body_noseed, mesh, in_specs=specs, out_specs=spec
                ))
        return jitted_by_nseg[n_seg]

    def fn(q, k, v, segment_ids=None, dropout_seed=None):
        if dropout_rate and dropout_seed is None:
            raise ValueError(
                "this wrapper was built with dropout_rate > 0; pass "
                "dropout_seed= per call (vary it per step)"
            )
        size = mesh.shape[sp]
        divisor = 2 * size if schedule == "zigzag" else size
        for name_, t in (("q", q), ("k", k), ("v", v)):
            if t.shape[1] % divisor != 0:
                raise ValueError(
                    f"{name_} sequence length {t.shape[1]} must be divisible "
                    f"by {divisor} ('{sp}' axis size {size}"
                    + (", ×2 chunks for zigzag)" if schedule == "zigzag"
                       else ") — pad the sequence")
                )
        if segment_ids is None:
            segs = ()
        elif isinstance(segment_ids, (tuple, list)):
            segs = tuple(jnp.asarray(s, jnp.int32) for s in segment_ids)
        else:
            segs = (jnp.asarray(segment_ids, jnp.int32),) * 2
        for s, ref in zip(segs, (q, k)):
            # Must match here, before the zigzag gather — JAX clamps
            # out-of-bounds gather indices, so a short segment array would
            # silently duplicate its tail instead of erroring.
            if s.shape != (ref.shape[0], ref.shape[1]):
                raise ValueError(
                    f"segment_ids shape {s.shape} != (batch, seq) = "
                    f"{(ref.shape[0], ref.shape[1])}"
                )
        sharding = NamedSharding(mesh, spec)
        seed_args = (
            (jnp.asarray(dropout_seed, jnp.uint32),) if dropout_rate else ()
        )
        if schedule == "zigzag":
            idxs = zigzag_indices(q.shape[1], size)
            inv = np.argsort(idxs)
            q, k, v = (jnp.asarray(t)[:, idxs] for t in (q, k, v))
            segs = tuple(s[:, idxs] for s in segs)
            args = [jax.device_put(t, sharding) for t in (q, k, v)]
            args += [jax.device_put(t, sharding) for t in segs]
            return _jitted(len(segs))(*args[:3], *seed_args, *args[3:])[:, inv]
        args = [jax.device_put(t, sharding) for t in (q, k, v)]
        args += [jax.device_put(t, sharding) for t in segs]
        return _jitted(len(segs))(*args[:3], *seed_args, *args[3:])

    return fn
