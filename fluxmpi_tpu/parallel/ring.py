"""Ring attention — sequence/context parallelism over a mesh axis.

Long-context scaling: the sequence dimension is sharded over a mesh axis
(``sp``), each device holds one block of Q/K/V, and K/V blocks rotate around
the ring via ``lax.ppermute`` (ICI neighbor hops) while each device
accumulates its queries' attention with a numerically-stable online softmax
(blockwise/flash accumulation). Peak memory is O(seq/n_devices) per device
and the K/V transfer overlaps with the block computation, which is exactly
the layout the TPU torus wants.

The reference framework never touches the sequence dimension (SURVEY.md §5
— DP-only); this module is the capability extension that makes long-context
training first-class on TPU, designed so the ``sp`` axis composes with the
``dp`` axis in one mesh (e.g. ``{"dp": 4, "sp": 2}``).

Shapes: ``q, k, v`` are ``(batch, seq_local, heads, head_dim)`` inside a
``shard_map`` whose in_specs shard the global sequence over ``axis_name``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import config
from ._compat import shard_map_unchecked

__all__ = ["ring_attention", "make_ring_attention", "ring_attention_fn"]

_NEG_INF = -1e30


def _block_attend(q, k, v, o, m, l, mask):
    """One blockwise online-softmax update.

    q: [b, sq, h, d]; k/v: [b, sk, h, d]; o: [b, sq, h, d];
    m/l: [b, sq, h]; mask: [sq, sk] boolean (True = attend) or None.
    """
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    # scores: [b, h, sq, sk] — contraction on head_dim, batched on (b, h)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if mask is not None:
        scores = jnp.where(mask[None, None, :, :], scores, _NEG_INF)
    m_block = jnp.max(scores, axis=-1)  # [b, h, sq]
    m_block = jnp.moveaxis(m_block, 1, -1)  # [b, sq, h]
    m_new = jnp.maximum(m, m_block)
    # renormalize previous accumulators
    alpha = jnp.exp(m - m_new)  # [b, sq, h]
    p = jnp.exp(scores - jnp.moveaxis(m_new, -1, 1)[:, :, :, None])  # [b,h,sq,sk]
    if mask is not None:
        p = jnp.where(mask[None, None, :, :], p, 0.0)
    l_new = l * alpha + jnp.moveaxis(jnp.sum(p, axis=-1), 1, -1)
    o_new = o * alpha[..., None] + jnp.moveaxis(
        jnp.einsum("bhqk,bkhd->bhqd", p, v), 1, 2
    )
    return o_new, m_new, l_new


def _ring_flash(q, k, v, *, name: str, causal: bool, n: int, idx):
    """Ring accumulation with the Pallas flash kernel as the local block
    attend (:func:`fluxmpi_tpu.ops.flash_attention_with_lse`).

    Each resident K/V block is attended by the flash kernel, which returns a
    *normalized* block output plus its logsumexp; blocks merge in plain JAX
    via the standard lse-weighted combine. The kernel's custom VJP honors
    the lse cotangent, so the whole ring differentiates exactly.
    """
    from ..ops.flash_attention import flash_attention_with_lse

    b, sq, h, d = q.shape
    o = jnp.zeros((b, sq, h, d), dtype=jnp.float32)
    lse = jnp.full((b, sq, h), _NEG_INF, dtype=jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def merge(o, lse, o_blk, lse_blk):
        # lse_blk arrives as (b, h, sq) from the kernel.
        lse_blk = jnp.moveaxis(lse_blk, 1, -1)
        lse_new = jnp.logaddexp(lse, lse_blk)
        w_prev = jnp.exp(lse - lse_new)[..., None]
        w_blk = jnp.exp(lse_blk - lse_new)[..., None]
        return o * w_prev + o_blk.astype(jnp.float32) * w_blk, lse_new

    def body(s, carry):
        o, lse, k_blk, v_blk = carry
        # After s rotations, the resident block originated on ring position
        # (idx - s) mod n.
        src = (idx - s) % n

        def full_blk(_):
            return flash_attention_with_lse(q, k_blk, v_blk, causal=False)

        if causal:
            def diag_blk(_):
                # Same ring position: global offsets cancel, local causal.
                return flash_attention_with_lse(q, k_blk, v_blk, causal=True)

            def skip_blk(_):
                return (
                    jnp.zeros((b, sq, h, d), q.dtype),
                    jnp.full((b, h, sq), _NEG_INF, jnp.float32),
                )

            o_blk, lse_blk = jax.lax.cond(
                src > idx,
                skip_blk,
                lambda _: jax.lax.cond(src == idx, diag_blk, full_blk, None),
                None,
            )
        else:
            o_blk, lse_blk = full_blk(None)

        o2, lse2 = merge(o, lse, o_blk, lse_blk)
        k_next = jax.lax.ppermute(k_blk, name, perm)
        v_next = jax.lax.ppermute(v_blk, name, perm)
        return o2, lse2, k_next, v_next

    o, lse, _, _ = jax.lax.fori_loop(0, n, body, (o, lse, k, v))
    return o.astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis_name: str | None = None,
    causal: bool = False,
    use_flash: bool = False,
) -> jnp.ndarray:
    """Blockwise ring attention; call inside ``shard_map`` with the sequence
    dimension of q/k/v sharded over ``axis_name``.

    Each of the ``n`` ring steps attends local queries to the K/V block
    currently resident, then rotates K/V to the next ring neighbor. With
    ``causal=True``, blocks strictly in the future are skipped via a zero
    mask (compiled as a select — no dynamic control flow).

    ``use_flash=True`` swaps the dense local block attend for the Pallas
    flash kernel (memory-optimal on-chip: the [sq, sk] score block never
    leaves VMEM); local sequence lengths must then divide the kernel's block
    sizes.
    """
    name = axis_name or config.SP_AXIS_NAME
    n = jax.lax.axis_size(name)
    idx = jax.lax.axis_index(name)
    b, sq, h, d = q.shape

    if use_flash:
        return _ring_flash(q, k, v, name=name, causal=causal, n=n, idx=idx)

    o = jnp.zeros_like(q, dtype=jnp.float32)
    m = jnp.full((b, sq, h), _NEG_INF, dtype=jnp.float32)
    l = jnp.zeros((b, sq, h), dtype=jnp.float32)

    qf = q.astype(jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(s, carry):
        o, m, l, k_blk, v_blk = carry
        # After s rotations, the resident block originated on ring position
        # (idx - s) mod n.
        src = (idx - s) % n
        kf = k_blk.astype(jnp.float32)
        vf = v_blk.astype(jnp.float32)
        if causal:
            q_pos = idx * sq + jnp.arange(sq)
            k_pos = src * k_blk.shape[1] + jnp.arange(k_blk.shape[1])
            mask = q_pos[:, None] >= k_pos[None, :]
        else:
            mask = None
        o2, m2, l2 = _block_attend(qf, kf, vf, o, m, l, mask)
        k_next = jax.lax.ppermute(k_blk, name, perm)
        v_next = jax.lax.ppermute(v_blk, name, perm)
        return o2, m2, l2, k_next, v_next

    o, m, l, _, _ = jax.lax.fori_loop(0, n, body, (o, m, l, k, v))
    # Guard fully-masked rows (l == 0) against 0/0.
    l = jnp.where(l == 0.0, 1.0, l)
    return (o / l[..., None]).astype(q.dtype)


def ring_attention_fn(
    axis_name: str | None = None,
    causal: bool = False,
    use_flash: bool = False,
):
    """An ``attention_fn`` drop-in for ``nn.MultiHeadDotProductAttention``.

    Use on a :class:`fluxmpi_tpu.models.TransformerEncoder` applied inside a
    ``shard_map`` whose in_specs shard the sequence over ``axis_name`` —
    every other encoder op (LayerNorm, MLP, residuals) is pointwise over the
    sequence, so only attention needs the ring. Explicit masks are not
    supported (use ``causal=True`` for causal masking; the mask is derived
    from global ring positions).

    Initialize parameters with a dense twin of the module (same config
    minus ``attention_fn`` — the parameter tree is identical) or inside the
    ``shard_map``: ``module.init`` outside it has no bound ``sp`` axis and
    raises ``NameError: unbound axis name``.
    """

    def fn(query, key, value, bias=None, mask=None, **kwargs):
        if bias is not None or mask is not None:
            raise ValueError(
                "ring_attention_fn derives masking from ring position; "
                "pass causal=True instead of an explicit mask/bias"
            )
        return ring_attention(
            query, key, value, axis_name=axis_name, causal=causal,
            use_flash=use_flash,
        )

    return fn


def make_ring_attention(
    mesh: Mesh | None = None,
    *,
    axis_name: str | None = None,
    causal: bool = False,
    batch_axis_name: str | None = None,
    use_flash: bool = False,
):
    """Wrap :func:`ring_attention` for eager use on mesh-sharded arrays.

    Returns ``fn(q, k, v) -> out`` where the inputs' sequence dimension
    (axis 1) is laid out over ``axis_name`` (and optionally batch over
    ``batch_axis_name``). Compiled once per shape.
    """
    from ..runtime import global_mesh

    mesh = mesh or global_mesh()
    sp = axis_name or config.SP_AXIS_NAME
    dp = batch_axis_name
    spec = P(dp, sp)

    def body(q, k, v):
        return ring_attention(
            q, k, v, axis_name=sp, causal=causal, use_flash=use_flash
        )

    mapped = shard_map_unchecked(
        body, mesh, in_specs=(spec, spec, spec), out_specs=spec
    )
    jitted = jax.jit(mapped)

    def fn(q, k, v):
        size = mesh.shape[sp]
        for name_, t in (("q", q), ("k", k), ("v", v)):
            if t.shape[1] % size != 0:
                raise ValueError(
                    f"{name_} sequence length {t.shape[1]} must be divisible "
                    f"by the '{sp}' mesh axis size {size} (pad the sequence)"
                )
        sharding = NamedSharding(mesh, spec)
        q, k, v = (jax.device_put(t, sharding) for t in (q, k, v))
        return jitted(q, k, v)

    return fn
