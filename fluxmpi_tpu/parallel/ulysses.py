"""Ulysses-style sequence parallelism — all-to-all head/sequence resharding.

The second canonical long-context layout (DeepSpeed-Ulysses; the goal
statement's "ring attention OR all-to-all sequence/context parallelism" —
this module supplies the latter, :mod:`fluxmpi_tpu.parallel.ring` the
former). The reference framework never touches the sequence dimension
(SURVEY.md §5), so like the ring this is a capability extension.

Mechanics, inside a ``shard_map`` whose in_specs shard the sequence over
``axis_name`` (n devices):

1. Q/K/V arrive ``[b, s/n, h, d]`` (sequence-sharded, all heads local).
2. One ``lax.all_to_all`` per tensor reshards to ``[b, s, h/n, d]`` —
   every device now holds the FULL sequence for ``h/n`` heads.
3. Plain (or Pallas flash) attention runs locally — no communication in
   the softmax, exact by construction (heads are independent).
4. One ``all_to_all`` back returns ``[b, s/n, h, d]``.

Trade-offs vs the ring: two all-to-alls of O(b·s·h·d/n) bytes per tensor
replace n ppermute hops; peak activation memory is O(s) per device for the
held heads (the ring keeps O(s/n)); the head count must be divisible by
the axis size. On small meshes with ICI all-to-all (a torus native), this
is usually faster than the ring for moderate sequences; the ring wins at
extreme lengths where O(s) per device no longer fits. Both compose with
``dp`` on the same mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ._compat import axis_size, shard_map_unchecked
from .plan import plan_axis_name
from .ring import _adapter_dropout, _fold_seed, _local_attend

__all__ = ["ulysses_attention", "make_ulysses_attention", "ulysses_attention_fn"]


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis_name: str | None = None,
    causal: bool = False,
    segment_ids=None,
    use_flash: bool = False,
    block_q: int | None = None,
    block_k: int | None = None,
    window: int | None = None,
    dropout_rate: float = 0.0,
    dropout_seed=None,
) -> jnp.ndarray:
    """All-to-all sequence-parallel attention; call inside ``shard_map``
    with the sequence dimension (axis 1) of q/k/v sharded over
    ``axis_name`` and the head dimension (axis 2) divisible by that axis'
    size.

    ``segment_ids``: optional int32 **local shards** ``[batch, seq_local]``
    (or a ``(q_seg, kv_seg)`` pair), flash-kernel convention (attend iff
    ids equal and key id nonzero, 0 = padding); they are all-gathered to
    the full sequence for the local attend (O(b·s) int32 — negligible).

    Grouped-query attention: K/V may carry fewer heads than Q
    (``h % h_kv == 0``); each tensor's own head axis is all-to-all'd, so
    both ``h`` and ``h_kv`` must divide the axis size. The local shard
    preserves the exact GQA group structure and moves ``h_kv/h`` of the
    full-head K/V bytes.

    ``window`` (sliding-window attention, requires ``causal=True``): the
    local attend sees the FULL sequence, so global positions and the flash
    kernel's O(seq·window) static tile skip both apply directly — this is
    the layout to use for windowed long-context (the ring cannot express a
    window through its flash path).

    Outside a bound axis (e.g. ``module.init``) this degrades to exact
    single-device attention, like the ring.
    """
    name = axis_name or plan_axis_name("sp")
    if window is not None and not causal:
        raise ValueError("window (sliding-window attention) requires causal=True")
    if dropout_rate and not use_flash:
        raise ValueError(
            "ulysses_attention dropout requires use_flash=True (in-kernel "
            "position-hash masks)"
        )
    if dropout_rate and dropout_seed is None:
        raise ValueError(
            "dropout_rate > 0 requires dropout_seed (an int or traced "
            "uint32 scalar)"
        )
    try:
        n = axis_size(name)
    except NameError:
        return _local_attend(
            q, k, v, causal=causal, segment_ids=segment_ids,
            use_flash=use_flash, block_q=block_q, block_k=block_k,
            window=window,
            dropout_rate=dropout_rate, dropout_seed=dropout_seed,
        )
    b, s_local, h, d = q.shape
    h_kv = k.shape[2]
    if h % n:
        raise ValueError(
            f"head count {h} must be divisible by the '{name}' axis size "
            f"{n} (Ulysses shards heads; use ring_attention otherwise)"
        )
    if h_kv != h and h_kv % n:
        # GQA: K/V carry h_kv < h heads. The all-to-all shards each
        # tensor's own head axis, so h_kv must divide too; the local shard
        # then keeps the exact group structure (local q head g attends
        # local kv head g // (h/h_kv)) and the flash kernel reads it
        # natively. (ADVICE r3: this used to surface as an opaque
        # all_to_all shape error.)
        raise ValueError(
            f"kv head count {h_kv} must be divisible by the '{name}' axis "
            f"size {n} (Ulysses shards kv heads too; use ring_attention "
            f"for grouped-KV layouts with fewer heads than devices)"
        )

    def seq_to_heads(t):
        # [b, s/n, h, d] → [b, s, h/n, d]: split heads across devices,
        # concatenate the sequence. all_to_all splits axis 2 and
        # concatenates along axis 1.
        return jax.lax.all_to_all(
            t, name, split_axis=2, concat_axis=1, tiled=True
        )

    def heads_to_seq(t):
        return jax.lax.all_to_all(
            t, name, split_axis=1, concat_axis=2, tiled=True
        )

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)

    seg_full = None
    if segment_ids is not None:
        if isinstance(segment_ids, (tuple, list)):
            qseg, kseg = segment_ids
        else:
            qseg = kseg = segment_ids
        qseg_f = jax.lax.all_gather(
            jnp.asarray(qseg, jnp.int32), name, axis=1, tiled=True
        )
        kseg_f = jax.lax.all_gather(
            jnp.asarray(kseg, jnp.int32), name, axis=1, tiled=True
        )
        seg_full = (qseg_f, kseg_f)

    # Fold the device index into the seed: each device holds a different
    # global head group but the same local (bh, q, k) coordinates.
    seed = (
        _fold_seed(dropout_seed, jax.lax.axis_index(name))
        if dropout_rate else None
    )
    out = _local_attend(
        qg, kg, vg, causal=causal, segment_ids=seg_full,
        use_flash=use_flash, block_q=block_q, block_k=block_k,
        window=window,
        dropout_rate=dropout_rate, dropout_seed=seed,
    )
    return heads_to_seq(out)


def ulysses_attention_fn(
    axis_name: str | None = None,
    causal: bool = False,
    use_flash: bool = False,
    block_q: int | None = None,
    block_k: int | None = None,
    window: int | None = None,
):
    """``attention_fn`` drop-in for ``nn.MultiHeadDotProductAttention``
    modules applied inside a sequence-sharding ``shard_map`` (same usage
    as :func:`fluxmpi_tpu.parallel.ring.ring_attention_fn`).

    Attention dropout runs in-kernel with masks independent per
    (batch, head): flax's ``broadcast_dropout=True`` default is NOT
    honored on this path (same caveat as
    :func:`fluxmpi_tpu.ops.flash_attention_fn`'s kernel impl)."""

    def fn(query, key, value, bias=None, mask=None, **kwargs):
        if bias is not None or mask is not None:
            raise ValueError(
                "ulysses_attention_fn derives masking from causal/"
                "segment_ids; pass causal=True instead of a mask/bias"
            )
        rate, seed = _adapter_dropout(kwargs)
        return ulysses_attention(
            query, key, value, axis_name=axis_name, causal=causal,
            use_flash=use_flash, block_q=block_q, block_k=block_k,
            window=window, dropout_rate=rate, dropout_seed=seed,
        )

    return fn


def make_ulysses_attention(
    mesh: Mesh | None = None,
    *,
    axis_name: str | None = None,
    causal: bool = False,
    batch_axis_name: str | None = None,
    use_flash: bool = False,
    block_q: int | None = None,
    block_k: int | None = None,
    window: int | None = None,
    dropout_rate: float = 0.0,
):
    """Eager wrapper over mesh-sharded arrays (mirror of
    :func:`fluxmpi_tpu.parallel.ring.make_ring_attention`). With
    ``dropout_rate > 0`` (requires ``use_flash=True``), pass
    ``dropout_seed=`` on each call."""
    from ..runtime import global_mesh

    mesh = mesh or global_mesh()
    sp = axis_name or plan_axis_name("sp")
    dp = batch_axis_name
    spec = P(dp, sp)
    if dropout_rate and not use_flash:
        raise ValueError(
            "make_ulysses_attention dropout requires use_flash=True"
        )

    def body(q, k, v, *seed):
        return ulysses_attention(
            q, k, v, axis_name=sp, causal=causal, use_flash=use_flash,
            block_q=block_q, block_k=block_k, window=window,
            dropout_rate=dropout_rate,
            dropout_seed=seed[0] if seed else None,
        )

    in_specs = (spec, spec, spec) + ((P(),) if dropout_rate else ())
    mapped = shard_map_unchecked(
        body, mesh, in_specs=in_specs, out_specs=spec
    )
    jitted = jax.jit(mapped)

    def fn(q, k, v, dropout_seed=None):
        if dropout_rate and dropout_seed is None:
            raise ValueError(
                "this wrapper was built with dropout_rate > 0; pass "
                "dropout_seed= per call (vary it per step)"
            )
        size = mesh.shape[sp]
        for name_, t in (("q", q), ("k", k), ("v", v)):
            if t.shape[1] % size != 0:
                raise ValueError(
                    f"{name_} sequence length {t.shape[1]} must be divisible "
                    f"by the '{sp}' mesh axis size {size} (pad the sequence)"
                )
            if t.shape[2] % size != 0:
                raise ValueError(
                    f"{name_} head count {t.shape[2]} must be divisible by "
                    f"the '{sp}' axis size {size} (Ulysses shards heads)"
                )
        sharding = NamedSharding(mesh, spec)
        q, k, v = (jax.device_put(t, sharding) for t in (q, k, v))
        if dropout_rate:
            return jitted(q, k, v, jnp.asarray(dropout_seed, jnp.uint32))
        return jitted(q, k, v)

    return fn
