"""Framework error types.

TPU-native analogue of the reference's ``FluxMPINotInitializedError``
(reference: src/FluxMPI.jl:59-63).
"""

from __future__ import annotations


class FluxMPINotInitializedError(RuntimeError):
    """Raised when a rank/world query is made before :func:`fluxmpi_tpu.init`.

    Mirrors the reference error struct and message intent
    (reference: src/FluxMPI.jl:59-63): the runtime must be brought up with
    ``init()`` before ``local_rank()`` / ``total_workers()`` are meaningful.
    """

    def __init__(self, message: str | None = None) -> None:
        super().__init__(
            message
            or "fluxmpi_tpu has not been initialized. Call `fluxmpi_tpu.init()` "
            "before querying `local_rank()` / `total_workers()` or using "
            "collectives."
        )


class CollectiveError(RuntimeError):
    """Raised when an eager collective cannot be lowered or executed."""
