"""Framework error types.

TPU-native analogue of the reference's ``FluxMPINotInitializedError``
(reference: src/FluxMPI.jl:59-63).
"""

from __future__ import annotations


class FluxMPINotInitializedError(RuntimeError):
    """Raised when a rank/world query is made before :func:`fluxmpi_tpu.init`.

    Mirrors the reference error struct and message intent
    (reference: src/FluxMPI.jl:59-63): the runtime must be brought up with
    ``init()`` before ``local_rank()`` / ``total_workers()`` are meaningful.
    """

    def __init__(self, message: str | None = None) -> None:
        super().__init__(
            message
            or "fluxmpi_tpu has not been initialized. Call `fluxmpi_tpu.init()` "
            "before querying `local_rank()` / `total_workers()` or using "
            "collectives."
        )


class CollectiveError(RuntimeError):
    """Raised when an eager collective cannot be lowered or executed."""


class FaultInjectedError(RuntimeError):
    """Raised by :mod:`fluxmpi_tpu.faults` when an armed fault schedule
    fires at a named site — the synthetic analogue of a transient I/O
    error, a dropped collective, or a killed fetch. Retry layers that
    tolerate real transient failures (checkpoint writes) treat it exactly
    like an ``OSError`` so chaos tests exercise the production path."""

    def __init__(self, site: str, hit: int, spec: str = "") -> None:
        self.site = site
        self.hit = hit
        super().__init__(
            f"fault injected at site {site!r} (hit {hit})"
            + (f" by schedule entry {spec!r}" if spec else "")
        )


class RequestRejectedError(RuntimeError):
    """Raised by :meth:`ServingRequest.result` / ``stream()`` when the
    serving engine rejected the request — queue full, drain, preemption,
    or engine shutdown. Carries ``reject_reason`` so callers can branch
    on the cause (retry a ``queue_full``, resubmit a ``preempted``
    elsewhere) without string-matching the message."""

    def __init__(self, reject_reason: str | None) -> None:
        self.reject_reason = reject_reason
        super().__init__(f"request rejected ({reject_reason})")


class TopologyMismatchError(ValueError):
    """Raised when an elastic restore cannot lay a checkpointed leaf out
    over the *current* mesh: a partition axis named by the saved (or
    supplied) partition spec is absent from the mesh, or the leaf
    dimension it shards is not divisible by the new axis size. The
    message names the leaf path, the offending dimension/axis, and both
    topologies so the operator can tell "resize the mesh" from "wrong
    checkpoint family" (see docs/fault_tolerance.md, "Elastic resume")."""


class CheckpointTimeoutError(RuntimeError):
    """Raised when a checkpoint save/wait exceeds the hard deadline set by
    ``FLUXMPI_TPU_CKPT_TIMEOUT`` — a background save wedged past the point
    where periodic warnings are useful (one process missing a
    cross-process barrier cannot be waited out)."""


class CheckpointDesyncError(RuntimeError):
    """Raised when processes disagree on the step number being
    checkpointed: banking the save would mix states from different steps
    into one "checkpoint". The save is aborted and the collective
    flight-recorder tail is dumped next to the checkpoint directory so the
    desync point can be localized (see docs/fault_tolerance.md)."""
