"""Dataset sharding and the device-feeding data loader.

TPU-native redesign of the reference's data layer (reference: src/data.jl).
The reference's ``DistributedDataContainer`` wraps any MLUtils-style
container, computes ``size_per_process = ceil(total / nworkers)``, takes the
contiguous partition of indices belonging to ``local_rank()``, and remaps
``getindex`` — the last rank holds the (smaller) remainder
(src/data.jl:13-26; asserted by test/test_data.jl:15-20). No communication at
iteration time.

Parity here is exact (same ceil-partition math, same remainder-on-last-rank),
with the world defaulting to the controller-process world: each process loads
only its shard, and :class:`DistributedDataLoader` assembles per-process
batches into **global** jax Arrays laid out over the data-parallel mesh axis
(``jax.make_array_from_process_local_data``) — the step from "each rank sees
its data" to "the compiled step sees one sharded global batch" that has no
analogue in MPI-land.
"""

from __future__ import annotations

import math
import os
import time
from typing import Any, Iterator, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import config
from . import faults as _faults
from .runtime import global_mesh
from .telemetry import get_registry as _telemetry_registry
from .telemetry import tracing as _tracing

__all__ = [
    "ArrayDataset",
    "DistributedDataContainer",
    "DistributedDataLoader",
    "scan_batches",
]

# device_gather="auto" staging budget: the replicated stage costs dataset
# bytes of device memory PER DEVICE, so auto only engages below this.
_DEVICE_GATHER_DEFAULT_MAX_BYTES = 256 * 1024 * 1024


def _device_gather_budget() -> int:
    """The FLUXMPI_TPU_DEVICE_GATHER_MAX_BYTES budget, defaulting (with a
    warning, not a crash) on a malformed value — a typo'd env var used to
    raise ValueError from deep inside epoch setup."""
    raw = os.environ.get("FLUXMPI_TPU_DEVICE_GATHER_MAX_BYTES")
    if not raw:
        return _DEVICE_GATHER_DEFAULT_MAX_BYTES
    try:
        return int(raw)
    except ValueError:
        import warnings

        warnings.warn(
            f"FLUXMPI_TPU_DEVICE_GATHER_MAX_BYTES={raw!r} is not an "
            f"integer byte count; falling back to the 256 MiB default",
            stacklevel=2,
        )
        return _DEVICE_GATHER_DEFAULT_MAX_BYTES


def _gather_batch(data: Any, perm: Any, start: Any, lbs: int) -> Any:
    """One batch from the device-resident dataset: a dynamic slice of the
    epoch permutation plus a per-leaf take. Pure and traceable — the ONE
    copy of the gather math, jit-wrapped per batch by the loader's
    device-gather path and traced INSIDE the fused-window program
    (:func:`fluxmpi_tpu.parallel.train.make_window_program`), so both
    paths consume identical batches by construction."""
    idx = jax.lax.dynamic_slice_in_dim(perm, start, lbs)
    return jax.tree_util.tree_map(lambda a: jnp.take(a, idx, axis=0), data)


class ArrayDataset:
    """A dataset backed by a pytree of equal-length host arrays.

    Samples are ``tree_map(lambda a: a[i], arrays)``. Loaders recognize this
    type (including wrapped in a :class:`DistributedDataContainer`) and
    assemble batches with the native C++ thread-pool gather
    (:mod:`fluxmpi_tpu.io`) instead of per-sample Python indexing.
    """

    def __init__(self, arrays: Any):
        leaves = jax.tree_util.tree_leaves(arrays)
        if not leaves:
            raise ValueError("ArrayDataset needs at least one array")
        n = len(leaves[0])
        for leaf in leaves:
            if len(leaf) != n:
                raise ValueError("all arrays must share the leading dimension")
        self.arrays = jax.tree_util.tree_map(np.ascontiguousarray, arrays)
        self._n = n

    def __len__(self) -> int:
        return self._n

    def __getitem__(self, i: int) -> Any:
        return jax.tree_util.tree_map(lambda a: a[i], self.arrays)


def _shard_bounds(total_size: int, rank: int, world: int) -> range:
    """Contiguous ceil-partition (reference: src/data.jl:14-19)."""
    size_per_process = math.ceil(total_size / world)
    n_partitions = math.ceil(total_size / size_per_process) if size_per_process else 0
    if rank >= n_partitions:
        # The reference errors here too (BoundsError on the partition list).
        raise IndexError(
            f"rank {rank} has no data shard: {total_size} samples across "
            f"{world} workers yields only {n_partitions} non-empty shards"
        )
    start = rank * size_per_process
    stop = min(start + size_per_process, total_size)
    return range(start, stop)


class DistributedDataContainer:
    """Shard any indexable dataset contiguously by worker rank.

    Reference: ``DistributedDataContainer`` (src/data.jl:8-26). ``data`` must
    support ``len`` and ``__getitem__``. Rank/world default to the
    controller-process world (each process loads its own shard; per-device
    slicing happens downstream in the loader via the mesh). Pass explicit
    ``rank``/``world`` to shard at any other granularity (e.g. per device).
    """

    def __init__(self, data: Any, *, rank: int | None = None, world: int | None = None):
        self.data = data
        if (rank is None) != (world is None):
            raise ValueError("pass rank and world together, or neither")
        if world is not None and (
            jax.process_count() > 1
            and world == jax.device_count()
            and rank == jax.process_index()
            and jax.local_device_count() > 1
        ):
            import warnings

            warnings.warn(
                "rank looks like a process index but world equals the global "
                "device count; with multiple chips per process these "
                "granularities differ — the default (no rank/world) shards "
                "per process, which is what the data loader expects.",
                stacklevel=2,
            )
        world = world if world is not None else jax.process_count()
        rank = rank if rank is not None else jax.process_index()
        self.rank = rank
        self.world = world
        self.total_size = len(data)
        self.idxs = _shard_bounds(self.total_size, rank, world)

    def min_shard_size(self) -> int:
        """Size of the smallest shard in this container's world (the last
        rank's remainder shard, or 0 when trailing ranks have empty shards)
        — every process can serve at least this many samples, which keeps
        multi-process iteration in lockstep."""
        spp = math.ceil(self.total_size / self.world)
        return max(0, self.total_size - (self.world - 1) * spp)

    def __len__(self) -> int:
        return len(self.idxs)  # reference: src/data.jl:24

    def __getitem__(self, i: int) -> Any:
        return self.data[self.idxs[i]]  # index remap, reference: src/data.jl:26

    def __iter__(self) -> Iterator[Any]:
        for i in range(len(self)):
            yield self[i]


def _stack_samples(samples: Sequence[Any]) -> Any:
    """Collate a list of samples (pytrees of arrays/scalars) into batched
    numpy arrays."""
    return jax.tree_util.tree_map(lambda *xs: np.stack(xs), *samples)


def _data_axis_size(mesh: Any, axis: Any) -> int:
    """Total device count along the loader's batch axis — a single mesh
    axis, or the product of a composed plan's data-axis tuple."""
    if isinstance(axis, tuple):
        return int(np.prod([mesh.shape.get(a, 1) for a in axis]))
    return mesh.shape.get(axis, 1)


class DistributedDataLoader:
    """Iterate global, mesh-sharded batches from per-process data.

    The TPU-side counterpart of putting a ``DistributedDataContainer`` inside
    ``MLUtils.DataLoader`` (reference README.md:47-49): each process draws
    samples from its shard, collates a per-process batch of
    ``global_batch_size / process_count``, and assembles a global
    ``jax.Array`` sharded over the data-parallel mesh axis so a jitted train
    step consumes it directly.

    Args:
      data: an indexable dataset (a :class:`DistributedDataContainer` for the
        usual per-process sharding, or any ``len``/``getitem`` container).
      global_batch_size: total batch across all workers; must divide by
        ``process_count`` (and the per-process batch by the local device
        count for even device layout).
      mesh: defaults to the runtime's global mesh.
      axis_name: mesh axis (or tuple of axes — a composed
        ``ParallelConfig``'s ``dp × fsdp`` data axes) to shard the batch
        dimension over. Default: the installed plan's data axes when
        ``init(parallel=)`` built the mesh, else the ``dp`` preference.
      shuffle/seed: reshuffle shard indices each epoch with a per-epoch key.
      global_shuffle: reshuffle the assignment of samples to workers each
        epoch — a seeded permutation of the FULL dataset, of which this
        process takes its contiguous slice (every process computes the
        same permutation, so no communication). The reference's fixed
        contiguous shards (src/data.jl:14-19) mean a worker only ever
        sees its own slice; global shuffling restores i.i.d. batches
        across the whole dataset. Requires ``data`` to be a
        :class:`DistributedDataContainer` (the full-dataset view is what
        gets permuted). Implies ``shuffle``.
      drop_last: drop the trailing incomplete batch (default True — a ragged
        final batch would retrigger XLA compilation).
      prefetch: keep this many global batches ahead of the consumer with
        their host→device transfers already initiated (device transfers are
        async in JAX: ``jax.make_array_from_process_local_data`` returns
        while the DMA is in flight). Depth 2 means the device never waits
        on the input pipeline as long as host assembly keeps up — the
        device-side completion of the C++ host-side prefetcher. 0 disables
        (each batch transfers on demand). Memory note: up to
        ``prefetch + 1`` global batches are resident/in-flight on device
        at once — for very large vision batches pass ``prefetch=1`` or
        ``0`` (see docs/gotchas.md, "Prefetch holds extra batches on
        device").
      transform: optional host-side hook applied to each assembled LOCAL
        batch (numpy) before the device transfer — the
        normalization/augmentation point, running on this process's CPU
        while the device executes the previous step (it composes with
        both prefetchers). Either ``transform(batch)`` or
        ``transform(batch, rng)``; the 2-arg form receives a
        ``np.random.Generator`` seeded from (seed, epoch, batch index,
        process index) — augmentations reproduce exactly across
        checkpoint resume (``set_epoch``) and draw independently on
        every process. Must preserve each leaf's leading (batch)
        dimension (checked).
      device_gather: produce batches with a jit-compiled on-device gather
        instead of host assembly + per-batch transfer. The array-backed
        dataset is staged into device memory ONCE (replicated per device,
        cached across epochs), the epoch permutation is transferred once
        per epoch, and each batch is then one cheap compiled dispatch —
        a dynamic slice of the permutation + a local gather, with the
        output already laid out over the data-parallel axis. This removes
        ALL per-batch host work (no ``np.stack``, no per-leaf
        ``device_put``), which is what the host pays for today as device
        counts grow. ``"auto"`` (default) enables it when the dataset is
        array-backed, single-process, has no ``transform``, and the
        staged bytes fit the ``FLUXMPI_TPU_DEVICE_GATHER_MAX_BYTES``
        budget (default 256 MiB — the replicated staging costs dataset
        bytes of HBM *per device*); ``True`` forces it (raises if the
        dataset is not array-backed or a ``transform`` is set; falls
        back to the host path under multi-process, where batch assembly
        is a cross-process collective); ``False`` keeps the host path.
        A ragged tail batch (``drop_last=False``) always assembles on
        the host — a short gather would retrigger XLA compilation.
      elastic_order: assign samples to global batches **batch-major** —
        global batch ``b`` covers positions ``[b*gbs, (b+1)*gbs)`` of the
        (possibly shuffled) full-dataset order, and each process takes
        its contiguous ``gbs/process_count`` slice of *that batch* — so
        which samples batch ``b`` holds does not depend on the process
        count. This is the topology-invariant order elastic resume needs
        for multi-process sample-exactness: after ``cursor`` batches,
        exactly the first ``cursor * gbs`` positions of the epoch order
        are consumed, on ANY process count (see docs/fault_tolerance.md,
        "Elastic resume"). Single-process iteration is already
        batch-major, so the flag only changes behavior under
        ``process_count > 1``, where it requires a default-sharded
        :class:`DistributedDataContainer` (the full-dataset view) and
        ``drop_last=True`` (the trailing ``total % gbs`` samples are
        dropped — the ragged-remainder round-down). With ``shuffle`` (or
        ``global_shuffle``) the order is the seeded full-dataset
        permutation, identical on every process. Default False: the
        reference's fixed contiguous shards.
      transform_with_rng: explicitly declare the transform's call shape:
        ``True`` → ``transform(batch, rng)``, ``False`` →
        ``transform(batch)``. Default ``None`` falls back to, in order:
        a ``transform_with_rng`` attribute on the callable itself, then
        signature inspection — a transform whose signature has **two or
        more REQUIRED positional parameters** (no default, not
        keyword-only) gets the rng; ``f(batch, eps=1e-6)`` or
        ``f(batch, *, training=False)`` does not. Un-inspectable
        callables (C extensions, some builtins) can't be classified and
        are assumed 1-arg with a warning — pass this parameter (or set
        the attribute) to silence it.

    Telemetry: each produced batch observes its host-side assembly +
    transfer-initiation latency into the ``data.batch_fetch_seconds``
    histogram, and the ``data.prefetch_depth`` gauge reads the ready
    batches the queue held at hand-off. The queue is filled by the same
    thread that drains it, so mid-epoch the gauge sits at ``prefetch``
    and drops only while the source warms up / runs dry — it reports the
    in-flight transfer window, not pipeline slack. Input-boundness is
    the ``data.batch_fetch_seconds`` histogram against
    ``train.step_seconds``: fetch latency rivaling step time means the
    device is waiting on the host. Recorded into
    :func:`fluxmpi_tpu.telemetry.get_registry`.
    """

    def __init__(
        self,
        data: Any,
        global_batch_size: int,
        *,
        mesh: Mesh | None = None,
        axis_name: str | None = None,
        shuffle: bool = False,
        global_shuffle: bool = False,
        seed: int = 0,
        drop_last: bool = True,
        prefetch: int = 2,
        device_gather: bool | str = "auto",
        elastic_order: bool = False,
        transform: Any = None,
        transform_with_rng: bool | None = None,
    ):
        if global_shuffle and not isinstance(data, DistributedDataContainer):
            raise ValueError(
                "global_shuffle reshuffles the sample→worker assignment, "
                "which needs the full-dataset view of a "
                "DistributedDataContainer; wrap the dataset in one"
            )
        self.elastic_order = bool(elastic_order)
        if self.elastic_order and jax.process_count() > 1:
            if not isinstance(data, DistributedDataContainer) or (
                data.world != jax.process_count()
                or data.rank != jax.process_index()
            ):
                raise ValueError(
                    "elastic_order needs the full-dataset view of a "
                    "default-sharded DistributedDataContainer (rank/world "
                    "matching the process world): the batch-major sample "
                    "assignment is computed from the whole dataset"
                )
            if not drop_last:
                raise ValueError(
                    "elastic_order requires drop_last=True: the trailing "
                    "total %% global_batch_size samples round down so the "
                    "epoch is a whole number of topology-invariant batches"
                )
        self.data = data
        self.mesh = mesh
        if axis_name is None:
            from .runtime import global_plan

            plan = global_plan()
            # The plan's data axes are the default ONLY when this loader
            # rides the plan's own mesh (mesh=None → the global mesh, or
            # an explicit mesh carrying the plan's axes); an ad-hoc
            # mesh= without those axes falls back to the dp preference
            # rather than constructing a spec its mesh cannot express.
            if plan is not None and plan.covers(mesh):
                axes = plan.data_axes
                axis_name = axes[0] if len(axes) == 1 else axes
            else:
                axis_name = config.DP_AXIS_NAME
        elif isinstance(axis_name, (list, tuple)):
            axis_name = (
                axis_name[0] if len(axis_name) == 1 else tuple(axis_name)
            )
        self.axis_name = axis_name
        if global_batch_size % jax.process_count() != 0:
            raise ValueError(
                f"global_batch_size {global_batch_size} must divide evenly "
                f"across {jax.process_count()} processes"
            )
        self.global_batch_size = global_batch_size
        self.local_batch_size = global_batch_size // jax.process_count()
        mesh_for_check = mesh
        if mesh_for_check is None:
            try:
                mesh_for_check = global_mesh()
            except Exception:
                mesh_for_check = None
        if mesh_for_check is not None:
            axis = self.axis_name
            axis_size = _data_axis_size(mesh_for_check, axis)
            if global_batch_size % axis_size != 0:
                raise ValueError(
                    f"global_batch_size {global_batch_size} must be divisible "
                    f"by the '{axis}' mesh axis size {axis_size} so every "
                    f"device gets an equal slice"
                )
        self.shuffle = shuffle or global_shuffle
        self.global_shuffle = global_shuffle
        self.seed = seed
        self.drop_last = drop_last
        if prefetch < 0:
            raise ValueError(f"prefetch must be >= 0, got {prefetch}")
        self.prefetch = prefetch
        if device_gather not in (True, False, "auto"):
            raise ValueError(
                f"device_gather must be True, False, or 'auto', got "
                f"{device_gather!r}"
            )
        if device_gather is True:
            if transform is not None:
                raise ValueError(
                    "device_gather=True is incompatible with transform= "
                    "(transforms run on host numpy batches); use "
                    "device_gather=False or 'auto'"
                )
            if self._array_backing() is None:
                raise ValueError(
                    "device_gather=True requires an array-backed dataset "
                    "(ArrayDataset, optionally inside a "
                    "DistributedDataContainer)"
                )
        self.device_gather = device_gather
        # (arrays-object, mesh) -> staged device pytree + compiled gather:
        # the stage-once half of the device-gather contract. Keyed by
        # identity so swapping datasets or meshes restages.
        self._gather_cache: tuple[Any, ...] | None = None
        self._sharding_cache: tuple[Mesh, NamedSharding] | None = None
        # Host-side augmentation hook — contract in the class docstring.
        self.transform = transform
        if transform is None:
            if transform_with_rng is not None:
                raise ValueError("transform_with_rng given without transform")
            self._transform_arity = 0
        else:
            if not callable(transform):
                raise ValueError("transform must be callable")
            # Explicit declaration wins: parameter, then an attribute flag
            # on the callable itself (lets a library transform declare its
            # own shape); signature inspection is only the fallback.
            if transform_with_rng is None:
                transform_with_rng = getattr(
                    transform, "transform_with_rng", None
                )
            if transform_with_rng is not None:
                self._transform_arity = 2 if transform_with_rng else 1
            else:
                import inspect

                try:
                    params = inspect.signature(transform).parameters.values()
                    # Only REQUIRED positional params decide the call
                    # shape: f(batch, eps=1e-6) or f(batch, *,
                    # training=False) is a 1-arg transform, not a request
                    # for the rng.
                    required = sum(
                        1 for p in params
                        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
                        and p.default is p.empty
                    )
                except (TypeError, ValueError):  # builtins, C callables
                    import warnings

                    warnings.warn(
                        "transform signature is not inspectable; assuming "
                        "transform(batch) without an rng. Pass "
                        "transform_with_rng= (or set a transform_with_rng "
                        "attribute on the callable) to declare its call "
                        "shape explicitly.",
                        stacklevel=2,
                    )
                    required = 1
                self._transform_arity = 2 if required >= 2 else 1
        self._epoch = 0
        # Resumable-iteration state (state_dict/load_state_dict): the
        # epoch whose permutation the current pass uses, the number of
        # batches handed to the consumer this pass, and a pending
        # mid-epoch start position installed by load_state_dict.
        self._iter_epoch = 0
        self._cursor = 0
        self._resume_cursor = 0
        # Per-process shard sizes can differ (ceil partition, remainder on
        # the last rank). jax.make_array_from_process_local_data is a
        # cross-process collective, so every process MUST yield the same
        # number of batches or iteration deadlocks mid-epoch. Compute the
        # common (minimum) serveable length once.
        if (
            self.elastic_order
            and jax.process_count() > 1
            and isinstance(data, DistributedDataContainer)
        ):  # pragma: no cover - multihost only
            # Batch-major epoch: total // gbs whole global batches, each
            # contributing exactly local_batch_size samples per process —
            # identical on every process by construction.
            self._common_len = (
                data.total_size // global_batch_size
            ) * self.local_batch_size
        elif isinstance(data, DistributedDataContainer):
            self._common_len = data.min_shard_size()
        elif jax.process_count() > 1:  # pragma: no cover - multihost only
            from .comm import host_allreduce

            self._common_len = int(
                host_allreduce(np.asarray(len(data)), op="min")
            )
        else:
            self._common_len = len(data)
        if not drop_last:
            remainder = self._common_len % self.local_batch_size
            global_remainder = remainder * jax.process_count()
            axis_size = (
                _data_axis_size(mesh_for_check, self.axis_name)
                if mesh_for_check is not None
                else 1
            )
            if global_remainder % axis_size != 0:
                raise ValueError(
                    f"drop_last=False leaves a final batch of "
                    f"{global_remainder} samples, not divisible by the "
                    f"'{self.axis_name}' mesh axis size {axis_size}; use "
                    f"drop_last=True or pad the dataset"
                )

    def __len__(self) -> int:
        if self.drop_last:
            return self._common_len // self.local_batch_size
        return math.ceil(self._common_len / self.local_batch_size)

    def set_epoch(self, epoch: int) -> None:
        """Pin the epoch counter that keys the per-epoch shuffle (and the
        global-shuffle worker assignment). Call after restoring a
        checkpoint so a resumed run draws the same sample order the
        uninterrupted run would have — the loader's counter is plain
        Python state and is NOT part of the checkpointed TrainState.
        For mid-epoch-exact resume use
        :meth:`state_dict`/:meth:`load_state_dict` instead."""
        self._epoch = int(epoch)
        self._iter_epoch = int(epoch)
        self._cursor = 0
        self._resume_cursor = 0

    def state_dict(self) -> dict[str, int]:
        """Iteration position as plain ints: the ``epoch`` whose
        permutation the current pass uses, the ``cursor`` of batches
        already handed to the consumer this pass, and the shuffle
        ``seed`` (restore-time validation). Captured at a batch boundary
        this is exactly "everything up to and including batch ``cursor``
        was consumed" — internal prefetch/read-ahead never counts, so
        the checkpointed position matches what the training loop
        actually dispatched (see docs/fault_tolerance.md)."""
        return {
            "epoch": self._iter_epoch,
            "cursor": self._cursor,
            "seed": self.seed,
        }

    def geometry(self) -> dict[str, int]:
        """The batch geometry a cursor's *meaning* depends on, as plain
        ints — banked next to :meth:`state_dict` (``train_loop`` merges
        both into its checkpoint payload, and the save-time manifest
        records a copy) so :meth:`load_state_dict` under a different
        topology can re-derive the cursor instead of misreading it."""
        return {
            "process_count": jax.process_count(),
            "global_batch_size": self.global_batch_size,
            "num_batches": len(self),
            "elastic_order": int(self.elastic_order),
        }

    def load_state_dict(self, state: dict[str, Any]) -> None:
        """Restore a :meth:`state_dict`: the next ``iter()`` replays
        ``epoch``'s permutation starting at batch ``cursor`` —
        mid-epoch-exact on the host, native, and device-gather paths
        (skipped batches are index arithmetic, nothing is fetched). A
        cursor at the end of the epoch resumes at the next epoch's
        first batch.

        Elastic resume: when ``state`` also carries the saving loader's
        :meth:`geometry` and it differs from this loader's (the run was
        preempted on N hosts and resumes on M, or the global batch size
        changed), the cursor is remapped through the **global sample
        offset** it denotes — ``cursor * saved global_batch_size``
        samples of the epoch were consumed — rounding DOWN to the last
        whole new-width batch; the few samples of a partial batch that
        get re-seen are counted and logged (none are skipped). The
        remapped position is sample-exact whenever the sample→batch
        assignment is topology-invariant: always in a single-process
        world, and under ``elastic_order=True`` across process counts
        (a warning names the caveat otherwise). A ``state`` without
        geometry (pre-elastic checkpoint) is assumed same-topology and
        fails with a topology-naming error if its cursor cannot fit."""
        seed = int(state.get("seed", self.seed))
        if seed != self.seed:
            raise ValueError(
                f"loader state was captured with seed {seed} but this "
                f"loader uses seed {self.seed}: the resumed sample order "
                f"would silently diverge from the interrupted run"
            )
        epoch = int(state["epoch"])
        cursor = int(state["cursor"])
        geom = self.geometry()
        saved_geom = {
            key: int(state[key]) for key in geom if key in state
        }
        have_geom = all(
            key in saved_geom
            for key in ("process_count", "global_batch_size", "num_batches")
        )
        if have_geom and any(saved_geom[k] != geom[k] for k in saved_geom):
            cursor = self._remap_cursor(cursor, saved_geom)
        elif cursor < 0 or cursor > len(self):
            hint = (
                " — the state carries no batch geometry (a pre-elastic "
                "checkpoint), so it can only resume on the topology that "
                f"saved it; this loader spans {geom['process_count']} "
                f"process(es) at global batch {geom['global_batch_size']}, "
                "and a cursor that does not fit usually means the saving "
                "run had a different process count or batch size"
                if not have_geom
                else ""
            )
            raise ValueError(
                f"cursor {cursor} out of range for a {len(self)}-batch "
                f"epoch{hint}"
            )
        if cursor >= len(self):  # epoch fully consumed: resume at the next
            epoch, cursor = epoch + 1, 0
        self._epoch = epoch
        self._iter_epoch = epoch
        self._cursor = cursor
        self._resume_cursor = cursor

    def _remap_cursor(self, cursor: int, saved: dict[str, int]) -> int:
        """N→M cursor remap (docs/fault_tolerance.md, "Elastic resume"):
        the banked cursor meant ``cursor * saved_gbs`` global samples of
        the epoch consumed; re-derive this loader's cursor from that
        offset, rounding down to the last whole new-width batch."""
        import warnings

        old_gbs = saved["global_batch_size"]
        old_len = saved["num_batches"]
        if cursor < 0 or cursor > old_len:
            raise ValueError(
                f"cursor {cursor} out of range for the saved "
                f"{old_len}-batch epoch (saved geometry: "
                f"{saved['process_count']} process(es), global batch "
                f"{old_gbs})"
            )
        if cursor >= old_len:
            # The saved pass was COMPLETE (the banked epoch count
            # includes it — train_loop's canonical form). It must stay
            # complete under the new width even when the new epoch
            # covers more samples (old ragged tail < new coverage):
            # landing mid-epoch would replay the tail of an
            # already-counted pass and double-count the epoch.
            return len(self)
        offset = cursor * old_gbs  # global samples consumed this epoch
        new_gbs = self.global_batch_size
        new_cursor = offset // new_gbs
        reseen = 0
        if new_cursor >= len(self):
            # An INCOMPLETE old pass (the complete case returned above)
            # whose offset reaches past the new geometry's whole-batch
            # coverage: the old epoch's last few samples fall into the
            # new width's ragged tail. They are dropped — the same fate
            # drop_last gives a fresh epoch's tail — but the round-down
            # contract promises counted skips, so say so.
            warnings.warn(
                f"elastic resume remapped the loader cursor {cursor} "
                f"(global batch {old_gbs}) past the new geometry's "
                f"whole-batch coverage ({len(self)} × {new_gbs}): the "
                f"interrupted epoch's remaining "
                f"{old_len * old_gbs - offset} sample(s) fall into the "
                f"new width's ragged tail and are dropped — resuming at "
                f"the next epoch",
                stacklevel=3,
            )
            new_cursor = len(self)
        else:
            reseen = offset - new_cursor * new_gbs
        # Sample-exactness needs a topology-invariant sample→batch
        # assignment on BOTH sides: a single-process world is batch-major
        # by construction, a multi-process one only under elastic_order.
        saved_batch_major = saved["process_count"] == 1 or bool(
            saved.get("elastic_order", 0)
        )
        here_batch_major = jax.process_count() == 1 or self.elastic_order
        if not (saved_batch_major and here_batch_major):
            warnings.warn(
                "elastic cursor remap with a multi-process side not "
                "built with elastic_order=True: fixed contiguous shards "
                "reassign samples to workers when the world resizes, so "
                "the resumed epoch is sample-exact only in expectation — "
                "construct multi-process loaders with elastic_order=True "
                "for the exact contract",
                stacklevel=3,
            )
        if reseen:
            warnings.warn(
                f"elastic resume remapped the loader cursor {cursor} "
                f"(global batch {old_gbs}, {saved['process_count']} "
                f"process(es)) to {new_cursor} (global batch {new_gbs}, "
                f"{jax.process_count()} process(es)); the offset lands "
                f"mid-batch, so {reseen} already-consumed sample(s) are "
                f"re-seen (rounded down to the last whole batch — none "
                f"skipped)",
                stacklevel=3,
            )
        return new_cursor

    @property
    def resume_cursor(self) -> int:
        """Batches of the restored pass the next ``iter()`` will skip —
        the normalized mid-epoch position :meth:`load_state_dict` set
        (0 when none pending). Consumers (``train_loop``) read this to
        seat their own per-pass accounting after a resume."""
        return self._resume_cursor

    def _sharding(self) -> NamedSharding:
        # Memoized per (mesh, axis): every batch of every epoch reuses ONE
        # NamedSharding object — constructing a fresh one per call was
        # per-batch garbage on the hot path, and a constant object lets
        # jit-consumers of the batches skip sharding re-hashing.
        mesh = self.mesh or global_mesh()
        cached = self._sharding_cache
        if cached is None or cached[0] is not mesh:
            cached = (mesh, NamedSharding(mesh, P(self.axis_name)))
            self._sharding_cache = cached
        return cached[1]

    @staticmethod
    def _container_source(
        cont: "DistributedDataContainer",
    ) -> tuple[Any, tuple[Any, int] | None]:
        """Batch source + native-gather backing for the full-dataset-view
        iteration orders (global_shuffle, elastic_order): `order` entries
        are GLOBAL dataset indices, so the backing offset is 0."""
        source = cont.data
        backing = (
            (source.arrays, 0) if isinstance(source, ArrayDataset) else None
        )
        return source, backing

    def _array_backing(self) -> tuple[Any, int] | None:
        """If the dataset is array-backed, return (array pytree, index
        offset) for the native gather fast path."""
        if isinstance(self.data, ArrayDataset):
            return self.data.arrays, 0
        if isinstance(self.data, DistributedDataContainer) and isinstance(
            self.data.data, ArrayDataset
        ):
            return self.data.data.arrays, self.data.idxs.start
        return None

    def _use_device_gather(self, backing: tuple[Any, int] | None) -> bool:
        """Resolve the ``device_gather`` spec against this epoch's batch
        source (policy in the class docstring)."""
        if self.device_gather is False or backing is None:
            return False
        if self.transform is not None:
            return False
        if jax.process_count() > 1:
            # Global batch assembly is a cross-process collective
            # (make_array_from_process_local_data); the device-gather path
            # is single-controller. Host path keeps multi-process correct.
            return False
        if self.device_gather == "auto":
            budget = _device_gather_budget()
            nbytes = sum(
                np.asarray(leaf).nbytes
                for leaf in jax.tree_util.tree_leaves(backing[0])
            )
            if nbytes > budget:
                return False
        return True

    def _gather_state(self, arrays: Any) -> tuple[Any, Any, Any]:
        """Stage the backing arrays into device memory (once — cached
        across epochs) and build the compiled per-batch gather.

        Returns ``(staged pytree, jitted gather, replicated sharding)``.
        The gather is ``(data, perm, start) -> batch``: a dynamic slice of
        the epoch permutation plus a local take per leaf, with the output
        pinned to the loader's batch sharding — ONE compiled dispatch per
        batch, no retrace across batches or epochs (``start`` is a traced
        scalar).
        """
        mesh = self.mesh or global_mesh()
        cached = self._gather_cache
        if cached is not None and cached[0] is arrays and cached[1] is mesh:
            return cached[2], cached[3], cached[4]
        replicated = NamedSharding(mesh, P())
        staged = jax.tree_util.tree_map(
            lambda a: jax.device_put(np.ascontiguousarray(a), replicated),
            arrays,
        )
        out_sharding = self._sharding()
        lbs = self.local_batch_size

        def gather(data, perm, start):
            return _gather_batch(data, perm, start, lbs)

        fn = jax.jit(gather, out_shardings=out_sharding)
        self._gather_cache = (arrays, mesh, staged, fn, replicated)
        return staged, fn, replicated

    # -- fused-window pass (train_loop fuse="window") -------------------
    #
    # The pipelined device-gather path still pays one host dispatch per
    # batch (the jitted gather) plus one per step. The fused-window
    # driver moves the WHOLE flush window on device — gathers and steps
    # alike traced into one program — so instead of iterating, it asks
    # the loader for the epoch's device-resident pieces and accounts
    # consumption explicitly. Same epoch order, same staged arrays, same
    # state_dict/resume contract as iterating.

    def fusible(self) -> bool:
        """Can the fused-window driver drive this loader? Requires the
        device-gather path to be active for the current dataset/mesh
        (array-backed, single-process, no ``transform``, within the
        staging budget) and an epoch of whole full-width batches (a
        ragged tail would need the host path mid-window)."""
        backing = (
            self._container_source(self.data)[1]
            if self.global_shuffle
            else self._array_backing()
        )
        if backing is None or not self._use_device_gather(backing):
            return False
        return len(self) * self.local_batch_size <= self._common_len

    def device_epoch(self) -> tuple[Any, Any, int]:
        """Begin one fused-window pass: resolve this epoch's order (the
        same seeded permutation iterating would use), stage the dataset
        into device memory (cached across epochs), and transfer the
        epoch permutation once. Returns ``(staged, perm, start)`` — the
        replicated dataset pytree, the replicated ``int32`` permutation
        (backing offset applied, global-index form), and the batch index
        to start from (a pending mid-epoch resume cursor, else 0).
        Advances the same epoch/cursor bookkeeping as ``iter()``; the
        caller reports consumption via :meth:`note_consumed`."""
        if not self.fusible():
            raise ValueError(
                "device_epoch() needs the device-gather path: an "
                "array-backed single-process dataset without transform=, "
                "within FLUXMPI_TPU_DEVICE_GATHER_MAX_BYTES, and a whole "
                "number of full batches per epoch"
            )
        order, _, backing = self._epoch_plan()
        epoch_now = self._epoch
        self._epoch += 1
        arrays, offset = backing
        staged, _, replicated = self._gather_state(arrays)
        lbs = self.local_batch_size
        perm = jax.device_put(
            np.asarray(order[: len(self) * lbs], dtype=np.int32)
            + np.int32(offset),
            replicated,
        )
        start = self._resume_cursor
        self._resume_cursor = 0
        self._iter_epoch = epoch_now
        self._cursor = start
        return staged, perm, start

    def note_consumed(self, n: int) -> None:
        """Advance the consumption cursor by ``n`` batches — the fused
        driver's analogue of the per-yield increment in ``__iter__``, so
        :meth:`state_dict` captured at a window boundary names exactly
        the batches dispatched (the resume contract)."""
        self._cursor += int(n)

    def _timed_batches(self) -> Iterator[Any]:
        """The batch source with per-batch fetch latency observed into the
        telemetry registry (host assembly + transform + the transfer
        initiation inside ``make_array_from_process_local_data``) and,
        when tracing is enabled, a ``data.fetch`` span per batch on the
        same timeline as ``train.step`` — fetch spans rivaling step
        spans is the input-bound picture, now visible in Perfetto."""
        from .telemetry.watchdog import notify_progress

        it = self._iter_batches()
        reg = _telemetry_registry()
        if not reg.enabled and not _tracing.get_tracer().enabled:
            # Zero-cost-when-off: no per-batch perf_counter reads or
            # histogram updates. The watchdog liveness tick stays — it is
            # one int increment and losing it would blind the stall
            # detector exactly on the fastest loops. The chaos hook is
            # the same one-attribute-read guard as the comm layer.
            while True:
                try:
                    batch = next(it)
                except StopIteration:
                    return
                if _faults.ARMED:
                    # AFTER the fetch so hit N maps to real batch N —
                    # the end-of-epoch StopIteration probe never counts
                    # (a step= schedule would otherwise drift one hit
                    # per epoch).
                    _faults.check("data.fetch")
                notify_progress()
                yield batch
        hist = reg.histogram("data.batch_fetch_seconds")
        # Key trace events by the ABSOLUTE batch position in the epoch
        # permutation: on a resumed pass the first fetched batch is batch
        # `resume_cursor`, not 0 (read here, before _iter_batches' first
        # next() consumes the pending cursor), so the resumed run's
        # data.fetch timeline lines up batch-for-batch with the
        # uninterrupted run it must reproduce.
        b = self._resume_cursor
        while True:
            t0 = time.perf_counter()
            try:
                batch = next(it)
            except StopIteration:
                return
            if _faults.ARMED:
                _faults.check("data.fetch")  # post-fetch: hit N == batch N
            t1 = time.perf_counter()
            hist.observe(t1 - t0)
            _tracing.add_complete_event("data.fetch", t0, t1, batch=b)
            # Each produced batch is a watchdog liveness tick: the source
            # is drained by the consuming loop itself, so a hung step
            # stops this too — which gives every loader-fed loop a
            # progress signal even without the metrics= hook.
            notify_progress()
            b += 1
            yield batch

    def __iter__(self) -> Iterator[Any]:
        it = self._timed_batches()
        # Per-batch gauge updates ride behind the registry's enabled
        # guard, resolved once per epoch — with telemetry off the yield
        # loop pays one None check per batch, no registry-handle lookup
        # (the same zero-cost-when-off contract as _timed_batches;
        # fluxlint rule unguarded-hot-path-instrumentation).
        reg = _telemetry_registry()
        depth = reg.gauge("data.prefetch_depth") if reg.enabled else None
        # `_cursor` counts batches HANDED TO THE CONSUMER — incremented at
        # the yield, never when the prefetcher reads ahead — so a
        # state_dict() taken at a batch boundary names exactly the batches
        # the training loop consumed (the resume contract).
        if not self.prefetch:
            if depth is not None:
                depth.set(0)
            for batch in it:
                self._cursor += 1
                yield batch
            return
        # Device-side prefetch (flax prefetch_to_device shape, mesh-sharded):
        # run the batch source ahead of the consumer so each global batch's
        # host→device transfer is in flight while the device executes the
        # current step. The queue holds `prefetch` batches beyond the one
        # handed out.
        from collections import deque

        queue: deque = deque()
        for batch in it:
            queue.append(batch)
            if len(queue) > self.prefetch:
                if depth is not None:
                    depth.set(len(queue) - 1)
                self._cursor += 1
                yield queue.popleft()
        while queue:
            if depth is not None:
                depth.set(len(queue) - 1)
            self._cursor += 1
            yield queue.popleft()

    def _epoch_plan(self) -> tuple[Any, Any, tuple[Any, int] | None]:
        """Resolve the CURRENT epoch's iteration order: ``(order, source,
        backing)`` where ``order`` indexes ``source`` (or, offset by
        ``backing[1]``, the backing arrays). One copy of the epoch-order
        policy, shared by the pipelined iterator (:meth:`_iter_batches`)
        and the fused-window pass (:meth:`device_epoch`) so both consume
        the exact same sample sequence. Reads ``self._epoch`` without
        advancing it — callers own the bookkeeping."""
        if (
            self.elastic_order
            and jax.process_count() > 1
            and isinstance(self.data, DistributedDataContainer)
        ):  # pragma: no cover - multihost only
            # Batch-major, topology-invariant assignment (class
            # docstring): this process's epoch order is its contiguous
            # local-batch slice of every whole global batch of the
            # full-dataset order — so batch b holds the same global
            # samples on any process count, and a remapped cursor names
            # an exact prefix of the epoch.
            cont = self.data
            total = cont.total_size
            if self.shuffle:
                rng = np.random.default_rng(self.seed + self._epoch)
                full_order = rng.permutation(total)
            else:
                full_order = np.arange(total)
            lbs = self.local_batch_size
            nfull = total // self.global_batch_size
            order = (
                full_order[: nfull * self.global_batch_size]
                .reshape(nfull, jax.process_count(), lbs)[
                    :, jax.process_index(), :
                ]
                .reshape(-1)
            )
            source, backing = self._container_source(cont)
        elif self.global_shuffle:
            # Same seeded permutation of the FULL dataset on every process
            # (no communication); this process takes the contiguous slice
            # of the permutation matching its ceil-partition bounds, so
            # shard sizes — and the lockstep batch count — are identical
            # to the fixed-shard layout.
            cont = self.data
            rng = np.random.default_rng(self.seed + self._epoch)
            perm = rng.permutation(cont.total_size)
            # Slice by the container's own ceil-partition bounds — shard
            # sizes (and the lockstep batch count) stay identical to the
            # fixed-shard layout by construction.
            order = perm[cont.idxs.start : cont.idxs.stop]
            source, backing = self._container_source(cont)
        else:
            source = self.data
            order = np.arange(len(source))
            if self.shuffle:
                rng = np.random.default_rng(self.seed + self._epoch)
                rng.shuffle(order)
            backing = self._array_backing()
        return order, source, backing

    def _iter_batches(self) -> Iterator[Any]:
        order, source, backing = self._epoch_plan()
        epoch_now = self._epoch  # the epoch the shuffle rngs above used
        self._epoch += 1
        sharding = self._sharding()

        # Mid-epoch resume (load_state_dict): start this pass at batch
        # `start` of the epoch permutation. Skipping is index arithmetic
        # on `order` — the skipped batches are never fetched — and the
        # transform rng / trace batch index stay keyed by the ABSOLUTE
        # batch position, so a resumed pass reproduces the uninterrupted
        # pass exactly on every path.
        start = self._resume_cursor
        self._resume_cursor = 0
        self._iter_epoch = epoch_now
        self._cursor = start

        nbatches = len(self)

        def _globalize(batch):
            return jax.tree_util.tree_map(
                lambda x: jax.make_array_from_process_local_data(
                    sharding, np.asarray(x)
                ),
                batch,
            )

        def _lead_dims(tree):
            # None marks a 0-d leaf (no batch dim) so the mismatch check
            # reports it instead of crashing on shape[0].
            return {
                tuple(path): (arr.shape[0] if arr.ndim else None)
                for path, arr in (
                    (p, np.asarray(x))
                    for p, x in jax.tree_util.tree_flatten_with_path(tree)[0]
                )
            }

        def _transformed(batch, b):
            if self.transform is None:
                return batch
            before = _lead_dims(batch)
            if self._transform_arity == 2:
                rng = np.random.default_rng(
                    [self.seed, epoch_now, b, jax.process_index()]
                )
                out = self.transform(batch, rng)
            else:
                out = self.transform(batch)
            after = _lead_dims(out)
            if before != after:
                raise ValueError(
                    "transform must preserve every leaf's leading (batch) "
                    f"dimension; got {after} from {before}"
                )
            return out

        if backing is not None and self._use_device_gather(backing):
            # Device-gather fast path: the staged dataset is already in
            # device memory (cached across epochs), the epoch permutation
            # transfers once, and each batch is one compiled dispatch —
            # zero per-batch host work. Indices are global (order + shard
            # offset) into the staged arrays, same as the native path.
            arrays, offset = backing
            staged, gather, replicated = self._gather_state(arrays)
            lbs = self.local_batch_size
            full = self._common_len // lbs
            if full > start:
                perm = jax.device_put(
                    np.asarray(order[: full * lbs], dtype=np.int32)
                    + np.int32(offset),
                    replicated,
                )
                for b in range(start, full):
                    yield gather(staged, perm, np.int32(b * lbs))
            if nbatches > full:
                # Ragged tail: a shorter gather would retrace; assemble the
                # one short batch on the host like the native path does.
                from .io import gather_rows

                leaves, treedef = jax.tree_util.tree_flatten(arrays)
                tail = order[full * lbs : self._common_len] + offset
                batch = jax.tree_util.tree_unflatten(
                    treedef, [gather_rows(leaf, tail) for leaf in leaves]
                )
                yield _globalize(batch)
            return

        if backing is not None:
            # Native fast path: one C++ prefetcher per array leaf assembles
            # the next batches on background threads while the device runs
            # the current step. The prefetcher only serves whole batches;
            # the ragged tail under drop_last=False is gathered directly so
            # the epoch yields exactly len(self) batches either way.
            from .io import NativePrefetcher, gather_rows

            arrays, offset = backing
            lbs = self.local_batch_size
            full = self._common_len // lbs
            leaves, treedef = jax.tree_util.tree_flatten(arrays)
            if full > start:
                epoch_order = order[start * lbs : full * lbs] + offset
                prefetchers = [
                    iter(NativePrefetcher(leaf, epoch_order, lbs))
                    for leaf in leaves
                ]
                for b, leaf_batches in enumerate(zip(*prefetchers)):
                    batch = jax.tree_util.tree_unflatten(
                        treedef, list(leaf_batches)
                    )
                    yield _globalize(_transformed(batch, start + b))
            if nbatches > full:
                tail = order[full * lbs : self._common_len] + offset
                batch = jax.tree_util.tree_unflatten(
                    treedef, [gather_rows(leaf, tail) for leaf in leaves]
                )
                yield _globalize(_transformed(batch, full))
            return

        for b in range(start, nbatches):
            # Cap at _common_len so every process yields the same local batch
            # size even when shard lengths differ (the ragged tail under
            # drop_last=False) — mismatched local sizes would break the
            # cross-process global-array assembly.
            stop = min((b + 1) * self.local_batch_size, self._common_len)
            idxs = order[b * self.local_batch_size : stop]
            batch = _stack_samples([source[int(i)] for i in idxs])
            yield _globalize(_transformed(batch, b))


def scan_batches(
    loader: "DistributedDataLoader", k: int
) -> Iterator[Any]:
    """Group consecutive loader batches into ``[k]``-stacked super-batches
    for :func:`fluxmpi_tpu.parallel.make_train_step` with
    ``scan_steps=k`` — the loader-side half of multi-step dispatch (one
    host→device dispatch drives k optimizer updates).

    The leading axis is scan time, not data: the stacked leaves are laid
    out ``P(None, <loader's batch axis>)``. A ragged trailing group
    (fewer than ``k`` batches left in the epoch) is dropped, mirroring
    the loader's ``drop_last`` rationale — a short scan axis would
    retrigger XLA compilation.
    """
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    mesh = loader.mesh or global_mesh()
    sharding = NamedSharding(mesh, P(None, loader.axis_name))
    group: list[Any] = []
    for batch in loader:
        group.append(batch)
        if len(group) == k:
            stacked = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *group
            )
            yield jax.device_put(stacked, sharding)
            group = []
