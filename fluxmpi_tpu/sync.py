"""Pytree synchronization — broadcast params/state from a root rank.

TPU-native redesign of the reference's recursive ``synchronize!``
(reference: src/synchronize.jl). The reference walks an arbitrary state tree
with Functors and issues one blocking ``MPI.Bcast!`` per numeric leaf
(src/synchronize.jl:15-17), with special dispatches for optimizer leaves,
scalars, array-of-arrays, and a catch-all no-op (src/synchronize.jl:35).

Here state trees are JAX pytrees, and the divergence that synchronization
must erase lives at the *controller process* level (per-process RNG or
host-side init divergence — the analogue of per-MPI-rank divergence; within
one process, device replicas cannot diverge because jit keeps them
consistent). ``synchronize`` therefore broadcasts from the root *process*
over the multi-host transport, and is the identity in a single-process world
(world size 1) — exactly the reference's behavior at ``size == 1``.

The leaf-dispatch semantics are preserved exactly:

- pytree containers (dict/NamedTuple/tuple/list, optax states, flax
  FrozenDict) → recurse (reference: src/synchronize.jl:10-13, 24-27; optax
  optimizer states are plain pytrees, so the reference's ``Optimisers.Leaf``
  special case falls out for free);
- numeric arrays → broadcast from root (src/synchronize.jl:15-17);
- object arrays of arrays → recurse elementwise (src/synchronize.jl:20-22);
- Python/numpy scalars → broadcast as 1-element array, return scalar
  (src/synchronize.jl:29-31);
- anything else (str/None/callables/Sentinels) → identity no-op
  (src/synchronize.jl:33-35).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

import jax
import jax.numpy as jnp

from .comm import host_bcast

__all__ = ["synchronize", "FluxModelWrapper", "FlatParamVector"]


def _sync_array(x: Any, root_rank: int) -> Any:
    """Broadcast one numeric array leaf from the root process.

    For device arrays the result is laid out **replicated over the global
    mesh** — the TPU meaning of "every worker now holds the root's value"
    (the reference's ``bcast!`` leaves every rank's buffer equal,
    src/synchronize.jl:15-17; here the workers are mesh devices, so the
    synced tree is immediately consumable by a mesh-sharded train step).
    """
    if isinstance(x, jax.Array):
        synced = host_bcast(np.asarray(jax.device_get(x)), root=root_rank)
        out = jnp.asarray(synced, dtype=x.dtype)
        from .runtime import is_initialized, global_mesh
        from jax.sharding import NamedSharding, PartitionSpec

        if is_initialized():
            return jax.device_put(
                out, NamedSharding(global_mesh(), PartitionSpec())
            )
        return jax.device_put(out, x.sharding)
    return host_bcast(np.asarray(x), root=root_rank)


def _sync_leaf(x: Any, root_rank: int) -> Any:
    if isinstance(x, (jax.Array,)) or (
        isinstance(x, np.ndarray) and x.dtype != object
    ):
        # dtype is available without a device→host transfer on both kinds.
        dtype = np.dtype(x.dtype) if isinstance(x, np.ndarray) else x.dtype
        if np.issubdtype(dtype, np.number) or np.issubdtype(dtype, np.bool_):
            return _sync_array(x, root_rank)
        return x
    if isinstance(x, np.ndarray) and x.dtype == object:
        # Array-of-arrays: recurse elementwise (reference:
        # src/synchronize.jl:20-22).
        out = np.empty_like(x)
        for idx in np.ndindex(x.shape):
            out[idx] = synchronize(x[idx], root_rank=root_rank)
        return out
    if isinstance(x, (bool, np.bool_)):
        return bool(host_bcast(np.asarray([x]), root=root_rank)[0])
    if isinstance(x, (int, float, complex, np.number)):
        synced = host_bcast(np.asarray([x]), root=root_rank)[0]
        return type(x)(synced) if not isinstance(x, np.number) else synced
    # Unknown leaf kinds are left alone (reference: src/synchronize.jl:35).
    return x


def _is_fuseable(x: Any) -> bool:
    """Array leaves that can ride a fused flat broadcast (numeric/bool jax
    or numpy arrays — the leaves `_sync_leaf` would broadcast)."""
    if isinstance(x, jax.Array):
        dtype = x.dtype
    elif isinstance(x, np.ndarray) and x.dtype != object:
        dtype = np.dtype(x.dtype)
    else:
        return False
    return bool(
        np.issubdtype(dtype, np.number) or np.issubdtype(dtype, np.bool_)
    )


def _replicated_put(x):
    from .runtime import is_initialized, global_mesh
    from jax.sharding import NamedSharding, PartitionSpec

    if is_initialized():
        return jax.device_put(x, NamedSharding(global_mesh(), PartitionSpec()))
    return jnp.asarray(x)


def _sync_fused(leaves, idxs, root_rank: int, out) -> None:
    """One host broadcast for a whole same-dtype group of array leaves
    (reference ComponentArrays ext: ext/FluxMPIComponentArraysExt.jl:6-9 —
    here the default path, VERDICT r2 next #9, collapsing the per-leaf
    O(#leaves) round trips of src/synchronize.jl:15-17 to O(#dtypes))."""
    from .runtime import is_initialized

    host = [
        np.ravel(np.asarray(jax.device_get(leaves[i]))) for i in idxs
    ]
    flat = np.concatenate(host) if len(host) > 1 else host[0]
    synced = host_bcast(flat, root=root_rank)
    any_device = any(isinstance(leaves[i], jax.Array) for i in idxs)
    # One host→device transfer for the group; leaves slice off it on-device.
    # Pre-init there is no mesh to replicate over — leaves instead keep
    # their original placement (x.sharding), matching the per-leaf path.
    synced_dev = (
        _replicated_put(synced) if any_device and is_initialized() else None
    )
    offset = 0
    for i in idxs:
        leaf = leaves[i]
        shape = np.shape(leaf)
        size = int(np.prod(shape)) if shape else 1
        if isinstance(leaf, jax.Array):
            if synced_dev is not None:
                out[i] = _replicated_put(
                    jnp.reshape(synced_dev[offset : offset + size], shape)
                )
            else:
                out[i] = jax.device_put(
                    synced[offset : offset + size].reshape(shape).astype(
                        leaf.dtype
                    ),
                    leaf.sharding,
                )
        else:
            out[i] = synced[offset : offset + size].reshape(shape)
        offset += size


def synchronize(tree: Any, *, root_rank: int = 0) -> Any:
    """Synchronize ``tree`` across all controller processes.

    Every process returns the root process's values. Call this after model /
    optimizer init (which may diverge per process) — the three setup calls of
    the reference quick-start (params, model state, optimizer state;
    reference README.md:43-44,54). Pure (returns a new tree); the reference's
    in-place mutation has no JAX analogue.

    Array leaves are fused into one flat host broadcast per dtype — the
    collective count is independent of the tree's leaf count (a
    ResNet-50-sized tree syncs in ~2 round trips, not ~270). Scalars and
    exotic leaves keep the reference's per-leaf dispatch semantics.
    """
    if isinstance(tree, FluxModelWrapper):
        return _sync_wrapped_model(tree, root_rank)
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves:
        return tree  # empty fast-path (reference: src/synchronize.jl:11)
    out: list[Any] = [None] * len(leaves)
    groups: dict[Any, list[int]] = {}
    for i, leaf in enumerate(leaves):
        if _is_fuseable(leaf):
            # Group key: dtype string — identical flatten order on every
            # process keeps the fused collectives aligned.
            dtype = (
                leaf.dtype if isinstance(leaf, jax.Array)
                else np.dtype(leaf.dtype)
            )
            groups.setdefault(str(dtype), []).append(i)
        else:
            out[i] = _sync_leaf(leaf, root_rank)
    for idxs in groups.values():
        _sync_fused(leaves, idxs, root_rank, out)
    return jax.tree_util.tree_unflatten(treedef, out)


# ---------------------------------------------------------------------------
# Wrapped-model adapter (reference: ext/FluxMPIFluxExt.jl + marker struct
# src/FluxMPI.jl:81-86). Flux models are arbitrary mutable structs the
# reference cannot dispatch on, hence the marker wrapper. The JAX analogue:
# most state is already a pytree, but user classes holding arrays in
# attributes are not — the wrapper walks their attributes.
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class FluxModelWrapper:
    """Marker wrapper for a non-pytree model object whose attributes hold
    state to synchronize (reference ``FluxMPIFluxModel``,
    src/FluxMPI.jl:84-86)."""

    model: Any


def _sync_object(obj: Any, root_rank: int, _depth: int = 0) -> Any:
    if _depth > 32:
        return obj
    treedef = jax.tree_util.tree_structure(obj)
    if not jax.tree_util.treedef_is_leaf(treedef) or not hasattr(obj, "__dict__"):
        # A registered pytree (or something without attributes): sync directly.
        return synchronize(obj, root_rank=root_rank)
    for name, value in vars(obj).items():
        if name.startswith("_"):
            continue
        vdef = jax.tree_util.tree_structure(value)
        if jax.tree_util.treedef_is_leaf(vdef) and hasattr(value, "__dict__"):
            setattr(obj, name, _sync_object(value, root_rank, _depth + 1))
        else:
            setattr(obj, name, synchronize(value, root_rank=root_rank))
    return obj


def _sync_wrapped_model(wrapped: FluxModelWrapper, root_rank: int) -> FluxModelWrapper:
    return FluxModelWrapper(_sync_object(wrapped.model, root_rank))


# ---------------------------------------------------------------------------
# Flat-parameter-vector adapter (reference: ext/FluxMPIComponentArraysExt.jl
# — sync a whole parameter tree with ONE collective on the flat underlying
# vector, rewrapping with the original axes).
# ---------------------------------------------------------------------------


class FlatParamVector:
    """A parameter tree flattened into one contiguous 1-D buffer.

    The ComponentArray analogue: ``synchronize`` (and any collective) touches
    the single flat vector — one collective for the whole tree instead of one
    per leaf (reference: ext/FluxMPIComponentArraysExt.jl:6-9).
    """

    def __init__(self, flat: jax.Array, shapes, treedef, sizes, dtypes=None) -> None:
        self.flat = flat
        self._shapes = shapes
        self._treedef = treedef
        self._sizes = sizes
        self._dtypes = dtypes

    @classmethod
    def from_tree(cls, tree: Any) -> "FlatParamVector":
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        shapes = [jnp.shape(l) for l in leaves]
        sizes = [int(np.prod(s)) if s else 1 for s in shapes]
        dtypes = [jnp.asarray(l).dtype for l in leaves]
        flat = (
            jnp.concatenate([jnp.ravel(jnp.asarray(l)) for l in leaves])
            if leaves
            else jnp.zeros((0,))
        )
        return cls(flat, shapes, treedef, sizes, dtypes)

    def to_tree(self) -> Any:
        leaves = []
        offset = 0
        dtypes = self._dtypes or [self.flat.dtype] * len(self._sizes)
        for shape, size, dtype in zip(self._shapes, self._sizes, dtypes):
            leaves.append(
                jnp.reshape(self.flat[offset : offset + size], shape).astype(dtype)
            )
            offset += size
        return jax.tree_util.tree_unflatten(self._treedef, leaves)

    def __len__(self) -> int:
        return int(self.flat.shape[0])


def _fpv_flatten(v: FlatParamVector):
    return (v.flat,), (v._shapes, v._treedef, v._sizes, v._dtypes)


def _fpv_unflatten(aux, children):
    shapes, treedef, sizes, dtypes = aux
    return FlatParamVector(children[0], shapes, treedef, sizes, dtypes)


jax.tree_util.register_pytree_node(FlatParamVector, _fpv_flatten, _fpv_unflatten)
