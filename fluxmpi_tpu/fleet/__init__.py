"""Fleet operations: coordinated actions ACROSS the hosts of one job.

The :mod:`fluxmpi_tpu.telemetry.fleet` plane *observes* a fleet (the
cross-host collector and straggler attribution); this package *operates*
on one. Its first citizen is :mod:`~fluxmpi_tpu.fleet.resize` — live
N→M world resizing: drain at a window boundary, bank a checkpoint,
restart under the new process count, reshard via the topology manifest,
and account every second of the pipeline as attributed badput.
"""

from . import resize  # noqa: F401

__all__ = ["resize"]
