"""Live N→M resizing: drain, save, reshard, resume — badput accounted.

Production fleets change size while a job runs: a slice is reclaimed, a
repaired host rejoins, an autoscaler trades capacity between jobs. The
training loop already knows how to *survive* that (elastic resume remaps
a checkpoint across topologies via the PR 6 manifest); this module makes
it an *operation* with a contract: a resize is requested explicitly,
honored at a window boundary (never mid-step), sample-exact across the
restart (the loader cursor remap — no example skipped or repeated), and
every second it costs is attributed to a named phase on a schema'd event
record instead of vanishing into "the job was slow today".

The pipeline and who runs each phase::

    OLD WORLD (N processes)                NEW WORLD (M processes)
    ----------------------                 -----------------------
    request_resize(M)      <- operator / autoscaler / SIGTERM+target
      | agreed at the next flush boundary (host max-reduce, the
      | coordinated-preemption pattern: every process stops at the
      | SAME update count)                   [phase: drain]
    drain in-flight window
    final checkpoint save + wait             [phase: save]
    write handoff stamp, exit cleanly
                  ...scheduler restarts the job with M processes...
                                             [phase: restart]
                                           resume reads the stamp,
                                           manifest-remapped restore
                                             [phase: reshard]
                                           complete + append the
                                           ``fluxmpi_tpu.resize/v1``
                                           record, remove the stamp

The **handoff stamp** (``.fluxmpi_resize.json`` next to the checkpoint
steps) is how a record spanning two process worlds gets stitched: the
draining world banks its phases and exit stamp there; the resumed world
computes ``restart`` (the gap neither world saw) from it, adds its own
``reshard`` seconds, validates the whole record against
:data:`~fluxmpi_tpu.telemetry.schema.RESIZE_SCHEMA`, and appends it to
the ``FLUXMPI_TPU_RESIZE=<path>`` JSONL bank that
``scripts/check_metrics_schema.py`` validates.

Wiring: ``init(resize=...)`` / ``FLUXMPI_TPU_RESIZE`` arms the plane
(``"1"`` = armed, a path = armed + record bank); ``train_loop`` polls
the coordinator at flush boundaries exactly like coordinated
preemption (one extra host max-reduce per flush, only while armed, and
only when a checkpoint manager is attached — there is nothing to
reshard from otherwise). Progress lands on the live exporter's RESIZE
board (``/status``, rendered by ``scripts/fluxmpi_top.py``) and the
``resize.*`` metric names (a closed schema namespace).

Chaos sites: ``resize.drain`` fires when the request is agreed (a
``delay=`` entry stalls the drain and shows up as drain-phase badput);
``resize.reshard`` fires on the resumed world before the restore's
bytes move.

SIGTERM composes rather than duplicates: a preemption drains and banks
a checkpoint through its own path; when a resize target is ALSO armed,
the same drain produces the handoff stamp, so "SIGTERM the old world,
restart with M processes" is a resize with the preemption grace window
as its drain trigger.
"""

from __future__ import annotations

import json
import os
import threading
import time
import warnings
from typing import Any

from ..telemetry.registry import process_index_or_zero as _process_index
from ..telemetry.registry import get_registry as _get_registry
from ..telemetry.schema import RESIZE_PHASES, RESIZE_SCHEMA

__all__ = [
    "ResizeCoordinator",
    "HANDOFF_FILENAME",
    "get_resize_coordinator",
    "set_resize_coordinator",
    "request_resize",
    "read_handoff",
    "configure",
    "enabled",
    "shutdown",
]

_ENV_VAR = "FLUXMPI_TPU_RESIZE"

# The cross-restart stitch point, written next to the step directories
# (the durable tier — the resumed world must see it on shared storage).
HANDOFF_FILENAME = ".fluxmpi_resize.json"


def _handoff_path(directory: str) -> str:
    return os.path.join(directory, HANDOFF_FILENAME)


def read_handoff(directory: str) -> dict[str, Any] | None:
    """The pending handoff stamp under ``directory``, or None (absent or
    unreadable — an unreadable stamp warns and reads as absent, the
    manifest discipline: telemetry corruption must never block a
    restore)."""
    path = _handoff_path(directory)
    try:
        with open(path) as f:
            stamp = json.load(f)
    except FileNotFoundError:
        return None
    except (OSError, ValueError) as exc:
        warnings.warn(
            f"unreadable resize handoff stamp at {path}: {exc}; treating "
            f"as absent (the resize record for this restart is lost)",
            stacklevel=2,
        )
        return None
    if not isinstance(stamp, dict) or stamp.get("schema") != RESIZE_SCHEMA:
        warnings.warn(
            f"resize handoff stamp at {path} has unexpected schema "
            f"{stamp.get('schema') if isinstance(stamp, dict) else stamp!r}; "
            f"treating as absent",
            stacklevel=2,
        )
        return None
    return stamp


class ResizeCoordinator:
    """One job's resize state machine: the request flag the loop polls,
    the per-phase badput ledger, and the handoff stamp protocol.

    Thread discipline: :meth:`request_resize` is a plain-attribute write
    (callable from a signal handler or an operator thread, the
    preemption-flag rule); everything else runs on the driver thread.

    Args:
      log_path: append one validated ``fluxmpi_tpu.resize/v1`` JSON line
        per completed resize here (None = no bank; the record still
        lands on the RESIZE board and ``resize.*`` gauges).
      enabled: arm immediately. The module default starts DISARMED —
        arm via ``init(resize=...)`` / ``FLUXMPI_TPU_RESIZE`` /
        :func:`configure`.
    """

    def __init__(
        self, *, log_path: str | None = None, enabled: bool = True
    ):
        self.enabled = enabled
        self.log_path = log_path
        self._target: int | None = None
        self._reason: str | None = None
        self._t0: float | None = None
        self._phase: str | None = None
        self._phase_seconds: dict[str, float] = {}
        self._lock = threading.Lock()

    # -- request flag (signal-safe writes, loop-polled reads) ----------

    def request_resize(self, target: int, *, reason: str = "api") -> None:
        """Ask the running world to drain and hand off to ``target``
        processes. Takes effect at the next flush boundary; a second
        request before then overwrites the first (last writer wins —
        the autoscaler's newest verdict is the one that matters)."""
        if not isinstance(target, int) or isinstance(target, bool) or target < 1:
            raise ValueError(
                f"resize target must be an int >= 1, got {target!r}"
            )
        self._reason = reason
        self._target = target

    def requested_target(self) -> int:
        """The locally-requested target world size, 0 when none — the
        value the loop max-reduces across processes at flush boundaries
        (any process's request enrolls the world)."""
        return self._target or 0

    def clear_request(self) -> None:
        self._target = None
        self._reason = None

    # -- phase ledger ---------------------------------------------------

    def begin(self, target: int, *, from_processes: int) -> None:
        """The request was agreed by the world: start the drain clock,
        fire the ``resize.drain`` chaos site (a ``delay=`` entry stalls
        here and books as drain badput), and post the board."""
        from .. import faults as _faults

        self._target = target
        self._t0 = time.perf_counter()
        self._phase = "drain"
        self._phase_seconds = {}
        self._count("resize.requests")
        self._note_board(
            phase="drain",
            to_processes=target,
            from_processes=from_processes,
            reason=self._reason,
        )
        _faults.check("resize.drain")

    def note_drained(self) -> float:
        """The in-flight window is drained: close the drain phase and
        open ``save``. Returns the drain seconds."""
        drain = (
            time.perf_counter() - self._t0 if self._t0 is not None else 0.0
        )
        self.note_phase("drain", drain)
        self._phase = "save"
        self._note_board(phase="save")
        return drain

    def note_phase(self, phase: str, seconds: float) -> None:
        """Attribute ``seconds`` of badput to ``phase`` (one of
        :data:`~fluxmpi_tpu.telemetry.schema.RESIZE_PHASES`) — the
        ledger, the ``resize.badput_seconds`` gauge, and the board."""
        if phase not in RESIZE_PHASES:
            raise ValueError(
                f"unknown resize phase {phase!r}; must be one of "
                f"{RESIZE_PHASES}"
            )
        with self._lock:
            self._phase_seconds[phase] = (
                self._phase_seconds.get(phase, 0.0) + seconds
            )
            total = dict(self._phase_seconds)
        reg = _get_registry()
        if getattr(reg, "enabled", True):
            reg.gauge("resize.badput_seconds", phase=phase).set(total[phase])
        self._note_board(phase_seconds=total)

    # -- handoff protocol ----------------------------------------------

    def write_handoff(
        self,
        directory: str,
        *,
        step: int,
        from_processes: int,
        to_processes: int,
    ) -> str | None:
        """Bank the draining world's half of the record next to the
        checkpoint (lead process writes, fsync'd — the stamp must
        survive the same crash the checkpoint does; peers no-op).
        Returns the stamp path (lead) or None."""
        self._phase = "handoff"
        with self._lock:
            phases = dict(self._phase_seconds)
        self._note_board(phase="handoff", step=step)
        if _process_index() != 0:
            return None
        stamp = {
            "schema": RESIZE_SCHEMA,
            "handoff": True,
            "step": int(step),
            "from_processes": int(from_processes),
            "to_processes": int(to_processes),
            "reason": self._reason or "api",
            "drain_seconds": float(phases.get("drain", 0.0)),
            "save_seconds": float(phases.get("save", 0.0)),
            "exit_unix": time.time(),
        }
        path = _handoff_path(directory)
        os.makedirs(directory, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(stamp, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return path

    def maybe_begin_reshard(self, directory: str) -> dict[str, Any] | None:
        """Called by the resumed world before its restore: when a
        handoff stamp is pending, fire the ``resize.reshard`` chaos
        site, post the board, and return the stamp (the caller times
        the restore and hands the seconds to :meth:`complete`). None
        when no resize is in flight."""
        stamp = read_handoff(directory)
        if stamp is None:
            return None
        from .. import faults as _faults

        self._phase = "reshard"
        self._note_board(
            phase="reshard",
            step=stamp.get("step"),
            from_processes=stamp.get("from_processes"),
            to_processes=stamp.get("to_processes"),
        )
        _faults.check("resize.reshard")
        return stamp

    def complete(
        self,
        directory: str,
        stamp: dict[str, Any],
        *,
        reshard_seconds: float,
        to_processes: int,
    ) -> dict[str, Any] | None:
        """Stitch the full record on the resumed world: ``restart`` is
        the wall-clock gap between the old world's exit stamp and this
        world reaching its restore, minus the reshard time already
        attributed. Validates against the schema, appends to the JSONL
        bank (lead process), removes the stamp, and posts the terminal
        board. Returns the record (every process) or None when the
        stamp is malformed."""
        now = time.time()
        try:
            exit_unix = float(stamp["exit_unix"])
            drain = float(stamp.get("drain_seconds", 0.0))
            save = float(stamp.get("save_seconds", 0.0))
            step = int(stamp["step"])
            from_processes = int(stamp["from_processes"])
        except (KeyError, TypeError, ValueError) as exc:
            warnings.warn(
                f"malformed resize handoff stamp: {exc}; dropping the "
                f"record for this resize",
                stacklevel=2,
            )
            self._remove_stamp(directory)
            return None
        restart = max(0.0, now - exit_unix - reshard_seconds)
        phases = {
            "drain": drain,
            "save": save,
            "reshard": float(reshard_seconds),
            "restart": restart,
        }
        record = {
            "schema": RESIZE_SCHEMA,
            "time_unix": now,
            "step": step,
            "from_processes": from_processes,
            "to_processes": int(
                stamp.get("to_processes") or to_processes
            ),
            "reason": stamp.get("reason") or None,
            "phases": phases,
            "badput_seconds": sum(phases.values()),
        }
        actual = int(to_processes)
        if record["to_processes"] != actual:
            # The scheduler gave a different world than requested (it
            # happens: capacity moved again mid-restart). The record
            # reports the world that actually resumed — that is the
            # resize that occurred — with the request kept in `reason`.
            record["reason"] = (
                f"{record['reason'] or 'api'} "
                f"(requested {record['to_processes']})"
            )
            record["to_processes"] = actual
        from ..telemetry.schema import validate_resize_record

        errors = validate_resize_record(record)
        if errors:  # pragma: no cover - producer bug guard
            warnings.warn(
                f"resize record failed its own schema: {errors}",
                stacklevel=2,
            )
        # The resumed world's ledger starts empty (fresh process): adopt
        # the stitched phases wholesale rather than note_phase-adding,
        # which would double-count anything the loop already attributed.
        with self._lock:
            self._phase_seconds = dict(phases)
        reg = _get_registry()
        if getattr(reg, "enabled", True):
            for phase, seconds in phases.items():
                reg.gauge("resize.badput_seconds", phase=phase).set(seconds)
        self._count("resize.completed")
        self._note_board(
            phase="completed",
            step=step,
            from_processes=from_processes,
            to_processes=record["to_processes"],
            badput_seconds=record["badput_seconds"],
            phase_seconds=phases,
        )
        if _process_index() == 0:
            if self.log_path:
                try:
                    with open(self.log_path, "a") as f:
                        f.write(json.dumps(record) + "\n")
                except OSError as exc:
                    warnings.warn(
                        f"cannot append resize record to "
                        f"{self.log_path}: {exc}",
                        stacklevel=2,
                    )
            self._remove_stamp(directory)
        self.clear_request()
        self._phase = None
        return record

    def _remove_stamp(self, directory: str) -> None:
        try:
            os.remove(_handoff_path(directory))
        except OSError:
            pass

    # -- telemetry ------------------------------------------------------

    def _count(self, name: str) -> None:
        reg = _get_registry()
        if getattr(reg, "enabled", True):
            reg.counter(name).inc()

    def _note_board(self, **fields: Any) -> None:
        try:
            from ..telemetry import export as _export

            exporter = _export.get_exporter()
        except Exception:  # pragma: no cover - board is best-effort
            return
        if exporter is not None:
            exporter.note_resize(**fields)

    # -- board/introspection -------------------------------------------

    @property
    def phase(self) -> str | None:
        """The current pipeline phase (None when no resize is live)."""
        return self._phase

    def phase_seconds(self) -> dict[str, float]:
        with self._lock:
            return dict(self._phase_seconds)

    def reset(self) -> None:
        """Drop request + ledger (shutdown's no-leak contract)."""
        self.clear_request()
        self._t0 = None
        self._phase = None
        with self._lock:
            self._phase_seconds = {}


# ---------------------------------------------------------------------------
# Module plane: a process-global coordinator + configure()/shutdown(), the
# same shape as every telemetry plane (env var, init kwarg, no state leaks
# across init/shutdown cycles).
# ---------------------------------------------------------------------------

_default = ResizeCoordinator(enabled=False)
_default_lock = threading.Lock()


def get_resize_coordinator() -> ResizeCoordinator:
    """The process-global resize coordinator (disarmed until
    configured)."""
    return _default


def set_resize_coordinator(
    coordinator: ResizeCoordinator,
) -> ResizeCoordinator:
    """Swap the default coordinator (returns the previous one)."""
    global _default
    with _default_lock:
        prev, _default = _default, coordinator
    return prev


def request_resize(target: int, *, reason: str = "api") -> None:
    """Ask the running world to resize to ``target`` processes — the
    operator/autoscaler entry point; honored at the next flush boundary
    of a loop running with a checkpoint manager and the plane armed."""
    _default.request_resize(target, reason=reason)


def enabled() -> bool:
    """Is the resize plane armed? One attribute read — what
    ``train_loop`` gates its per-flush poll on."""
    return _default.enabled


def configure(spec: Any = None) -> ResizeCoordinator | None:
    """Wire the resize plane from a one-value spec (mirror of
    :func:`fluxmpi_tpu.telemetry.configure`):

    - ``None`` — read ``FLUXMPI_TPU_RESIZE`` (same forms; no-op when
      unset/empty);
    - ``False`` / ``"0"`` — disarm and drop any pending request;
    - ``True`` / ``"1"`` — arm the plane (records land on the board and
      gauges only);
    - a path string — arm, and append one ``fluxmpi_tpu.resize/v1``
      JSON line per completed resize there;
    - a :class:`ResizeCoordinator` — install it (armed).

    Called by ``fluxmpi_tpu.init(resize=...)``; idempotent.
    """
    if spec is None:
        spec = os.environ.get(_ENV_VAR)
        if spec is None or spec == "":
            return _default if _default.enabled else None
    if spec is False or spec == "0":
        shutdown()
        return None
    if isinstance(spec, ResizeCoordinator):
        spec.enabled = True
        set_resize_coordinator(spec)
        return spec
    if spec is True or spec == "1":
        _default.enabled = True
        return _default
    if isinstance(spec, str):
        _default.enabled = True
        _default.log_path = spec
        return _default
    raise ValueError(
        f"resize spec must be a bool, '0'/'1', a record-bank path, or a "
        f"ResizeCoordinator; got {spec!r}"
    )


def shutdown() -> None:
    """Disarm the default coordinator and drop its request/ledger — a
    resize request left armed across an init/shutdown cycle would drain
    the NEXT run at its first flush (the fault-plane leak rule)."""
    _default.enabled = False
    _default.log_path = None
    _default.reset()
