"""Checkpoint / resume.

The reference has no checkpoint subsystem; its enabling primitive is
``synchronize!`` — load state on the root rank, broadcast to all
(SURVEY.md §5; reference src/synchronize.jl). Here that pattern becomes a
first-class pair with two layouts handled transparently:

- **Replicated** state (plain DP): :func:`save_checkpoint` writes from the
  lead process via orbax; :func:`restore_checkpoint` reads it and
  re-synchronizes/replicates over the mesh — the exact
  load-on-root-then-broadcast flow, one call.
- **Sharded** state (FSDP/TP layouts from
  :mod:`fluxmpi_tpu.parallel.sharding`): saved and restored through orbax's
  sharding-aware ``StandardCheckpointer`` — every process writes/reads only
  its own shards, and restore lands each leaf directly in its training
  ``NamedSharding``; the state never gathers onto one host (VERDICT r1
  weak #5).

Crash consistency (the commit protocol, see docs/fault_tolerance.md):
every save writes into a ``<path>.tmp`` staging directory, renames it to
``<path>``, then fsyncs the sibling layout marker — the **COMMIT
marker**. Discovery (``all_steps``/``latest_step``/``restore``) only
believes committed steps, so a crash never yields a partial that
restores garbage. An overwrite of an existing path decommits the old
state only AFTER the new bytes are fully staged — a failed or crashed
write leaves the previous committed checkpoint untouched; the one
residual window is the few metadata ops between decommit and the
marker (old gone, new staged-but-uncommitted — discovery skips it and
startup quarantines it, same as the ``ckpt.commit`` crash window). Transient write failures retry with capped exponential
backoff (``FLUXMPI_TPU_CKPT_RETRIES`` / ``..._RETRY_BACKOFF_S``), and
the whole protocol is exercised under :mod:`fluxmpi_tpu.faults` sites
``ckpt.write`` / ``ckpt.commit`` / ``ckpt.read``.

Multi-process contract: the checkpoint path must live on storage
**shared by every process** (GCS/NFS — the standard orbax layout). The
commit marker, discovery, startup quarantine, and the peer-failure abort
sentinels all read the filesystem at the path, so a per-host local disk
would leave non-lead processes blind to commits and aborts alike.

Elasticity (PR 6, docs/fault_tolerance.md "Elastic resume"): every save
also writes a schema-validated ``<path>.manifest.json``
(:mod:`fluxmpi_tpu.utils.manifest`) recording global leaf
shapes/dtypes/partition specs, the save-time mesh and process count, and
— for ``train_loop`` payloads — the loader position + batch geometry.
``restore_checkpoint(..., mesh=..., rule=...)`` uses it to build the
resharding template internally, so a checkpoint written on N hosts
restores on M without a like-tree from the old world; the manifest is
written between the data rename and the commit marker (fault site
``ckpt.manifest``), so every *committed* step has one.
"""

from __future__ import annotations

import contextlib
import glob
import json
import os
import re
import shutil
import threading
import time
import warnings
from concurrent.futures import Future, ThreadPoolExecutor
from concurrent.futures import TimeoutError as _FutureTimeout
from typing import Any

import jax
import numpy as np

from .. import faults as _faults
from ..errors import CheckpointDesyncError, CheckpointTimeoutError
from ..errors import FaultInjectedError
from ..sync import synchronize
from ..telemetry import get_registry as _telemetry_registry
from . import manifest as _manifest

__all__ = ["save_checkpoint", "restore_checkpoint", "CheckpointManager"]

_ENV_TIMEOUT = "FLUXMPI_TPU_CKPT_TIMEOUT"
_ENV_RETRIES = "FLUXMPI_TPU_CKPT_RETRIES"
_ENV_BACKOFF = "FLUXMPI_TPU_CKPT_RETRY_BACKOFF_S"
_ENV_ASYNC = "FLUXMPI_TPU_CKPT_ASYNC"
_ENV_LOCAL_DIR = "FLUXMPI_TPU_CKPT_LOCAL_DIR"
_BACKOFF_CAP_S = 5.0

# Injectable sleep (the watchdog's injectable-clock discipline): retry
# tests monkeypatch this so backoff is asserted, not waited for.
_retry_sleep = time.sleep


def _goodput_segment(name: str):
    """Goodput-bucket context for checkpoint I/O — the run-health plane's
    view of save/restore wall time (``checkpoint_save`` /
    ``checkpoint_restore`` badput). A shared no-op when the tracker is
    disabled (the default) or when called off the training driver thread
    (an async background save overlaps training and is deliberately NOT
    booked — see telemetry/goodput.py)."""
    from ..telemetry import goodput as _goodput

    return _goodput.segment(name)


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def _hard_deadline_s() -> float | None:
    """Optional hard cap on checkpoint waits (``FLUXMPI_TPU_CKPT_TIMEOUT``
    seconds; unset/empty/0 = off, the historical warn-forever behavior)."""
    raw = os.environ.get(_ENV_TIMEOUT)
    if not raw:
        return None
    deadline = float(raw)
    return deadline if deadline > 0 else None


def _wait_with_diagnostic(
    fut: Future, what: str, warn_after_s: float = 60.0
) -> None:
    """``fut.result()`` that surfaces a wedge instead of hanging silently:
    a background save that never completes (e.g. one process missing a
    cross-process barrier) cannot be forced to finish, but the periodic
    warning turns an inexplicable hang into a diagnosable one (ADVICE r3).
    With ``FLUXMPI_TPU_CKPT_TIMEOUT`` set, the wait gives up past that
    deadline and raises :class:`~fluxmpi_tpu.errors.CheckpointTimeoutError`
    instead of warning forever — for orchestrators that would rather
    fail-fast and reschedule than hold a wedged slot."""
    deadline = _hard_deadline_s()
    waited = 0.0
    while True:
        timeout = warn_after_s
        if deadline is not None:
            timeout = min(timeout, max(deadline - waited, 0.001))
        try:
            fut.result(timeout=timeout)
            return
        except _FutureTimeout:
            waited += timeout
            if deadline is not None and waited >= deadline:
                raise CheckpointTimeoutError(
                    f"{what} did not complete within the "
                    f"{_ENV_TIMEOUT}={deadline:.0f}s hard deadline — "
                    f"giving up on a probable cross-process barrier wedge "
                    f"(a peer process exited or diverged; see the "
                    f"watchdog/flight-recorder dumps for which collective "
                    f"it died in)"
                ) from None
            warnings.warn(
                f"{what} has not completed after {waited:.0f}s — possible "
                f"cross-process barrier wedge (a peer process may have "
                f"exited or diverged); still waiting",
                stacklevel=2,
            )


def _with_write_retries(fn, what: str, *, collective: bool = False) -> None:
    """Run a checkpoint write attempt with capped exponential backoff on
    transient failures (``OSError`` — and :class:`FaultInjectedError`,
    which is how chaos tests exercise exactly this loop). Each retry
    bumps the ``checkpoint.retries`` counter. ``collective=True``
    disables the retry loop entirely: in a multi-process world *both*
    orbax save paths run cross-process coordination internally (multihost
    sync barriers), and one process re-entering the save unilaterally
    pairs those barriers with nobody — the retry attempt itself wedges,
    so no retry cap would ever be reached while the peers advance to the
    post-write barrier. A transient multi-process failure instead aborts
    the whole save through the peer-failure protocol
    (cross-process-consistent, previous committed checkpoint intact);
    the caller retries the *entire* save if it wants another attempt."""
    retries = 0 if collective else int(os.environ.get(_ENV_RETRIES, "3"))
    delay = float(os.environ.get(_ENV_BACKOFF, "0.1"))
    for attempt in range(retries + 1):
        try:
            if _faults.ARMED:
                _faults.check("ckpt.write")
            fn()
            return
        except (OSError, FaultInjectedError) as exc:
            if attempt >= retries:
                raise
            try:
                reg = _telemetry_registry()
                if reg.enabled:
                    reg.counter("checkpoint.retries").inc()
            except Exception:
                pass
            warnings.warn(
                f"{what} attempt {attempt + 1} failed transiently "
                f"({exc!r}); retrying in {min(delay, _BACKOFF_CAP_S):.2f}s "
                f"({retries - attempt} retr"
                f"{'y' if retries - attempt == 1 else 'ies'} left)",
                stacklevel=3,
            )
            _retry_sleep(min(delay, _BACKOFF_CAP_S))
            delay *= 2.0


def _process_barrier(name: str) -> None:
    """Cross-process barrier over the coordination service — NOT a device
    collective. CheckpointManager runs saves on a background thread; a
    device collective there could be submitted in a different order than
    the main thread's train-step collectives on another process, and JAX
    multi-controller deadlocks on submission-order inversion. The
    coordination-service barrier has no device program, so thread timing
    cannot invert anything."""
    if jax.process_count() <= 1:
        return
    try:  # pragma: no cover - multihost only
        from orbax.checkpoint import multihost

        multihost.sync_global_processes(name)
    except Exception:  # pragma: no cover - very old orbax
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


def _peer_write_failures(tmp: str) -> list[int]:
    """The ranks whose write attempt terminally failed, read from the
    ``<tmp>.write_failed.<rank>`` sentinels on the shared checkpoint
    storage (every process calls this after the post-write barrier, so
    all sentinels have landed). The signal deliberately rides the
    checkpoint filesystem, NOT a collective: the abort decision is made
    inside :func:`save_checkpoint`, which runs on the
    :class:`CheckpointManager` background thread for async saves — a
    device collective there is the submission-order inversion
    :func:`_process_barrier` exists to avoid. The flip side is that the
    sentinel must be visible to every process, which the shared-storage
    contract (module docstring) guarantees. Module-level so chaos tests
    can monkeypatch a failed peer."""
    return sorted(
        int(s.rsplit(".", 1)[-1])
        for s in glob.glob(glob.escape(tmp) + ".write_failed.*")
    )


def _fsync_dir(path: str) -> None:
    """fsync a directory so renames/creates/removals of its entries are
    durable — fsyncing a new file orders its *bytes*, but the directory
    entry itself (and a rename) lives in the parent's metadata, which
    journaling filesystems may commit seconds later. Without this, a
    power cut after ``save_checkpoint`` returns could surface a world
    where the OLD checkpoint's decommit persisted but the new rename +
    marker did not — no committed checkpoint at all. Best-effort: object
    stores and exotic platforms without directory fds skip silently
    (their rename/visibility semantics differ anyway)."""
    try:
        fd = os.open(path, os.O_RDONLY | getattr(os, "O_DIRECTORY", 0))
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:  # pragma: no cover - platform-dependent
        pass
    finally:
        os.close(fd)


def _is_sharded_tree(tree: Any) -> bool:
    """True when any leaf is laid out non-replicated over >1 device (an
    FSDP/TP state) — the layouts that must never host-gather."""
    return any(
        isinstance(l, jax.Array)
        and len(l.sharding.device_set) > 1
        and not l.is_fully_replicated
        for l in jax.tree_util.tree_leaves(tree)
    )


def _layout_marker_path(path: str) -> str:
    # A sibling of the checkpoint directory, never inside it: orbax
    # interprets directory contents as checkpoint tree entries.
    return path.rstrip(os.sep) + ".fluxmpi_layout"


def _write_layout_marker(path: str, layout: str) -> None:
    """Write the layout marker — the COMMIT point of the save protocol.
    fsync'd so a machine crash right after the rename cannot leave a
    marker the filesystem later loses while keeping the (older) rename:
    once this returns, the step is durably committed."""
    if jax.process_index() == 0:
        marker = _layout_marker_path(path)
        with open(marker, "w") as f:
            f.write(layout)
            f.flush()
            os.fsync(f.fileno())
        # The file fsync made the marker's BYTES durable; its directory
        # entry is parent metadata and needs its own fsync before the
        # "once this returns, the step is durably committed" claim holds.
        _fsync_dir(os.path.dirname(marker))


def _read_layout_marker(path: str) -> str | None:
    marker = _layout_marker_path(path)
    if os.path.exists(marker):
        with open(marker) as f:
            return f.read().strip()
    return None


def _check_layout(path: str, expected: str) -> None:
    saved = _read_layout_marker(path)
    if saved is not None and saved != expected:
        raise ValueError(
            f"checkpoint at {path} was saved with {saved} layout but the "
            f"restore template is {expected}: restoring a sharded (FSDP/TP) "
            "checkpoint needs a `like` tree carrying the training shardings "
            "(and vice versa) — re-shard the template with shard_tree, or "
            "pass allow_layout_change=True to cross layout families "
            "deliberately"
        )


def _save_sharded(path: str, state: Any, force: bool) -> None:
    import orbax.checkpoint as ocp

    # orbax's own force handles primary-host deletion behind cross-process
    # barriers — no hand-rolled rmtree (which would race non-zero ranks
    # into save()'s exists-check).
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, state, force=force)
    ckptr.wait_until_finished()


def _sds_template(like: Any) -> Any:
    """Restore template carrying the TARGET's sharding on every jax leaf —
    orbax then reshards to it deterministically instead of consulting the
    checkpoint's saved sharding file (which references the SAVE topology's
    devices and is unsafe to apply on a different one)."""
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        if isinstance(x, jax.Array)
        else x,
        like,
    )


def _restore_sharded(path: str, like: Any) -> Any:
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer().restore(path, _sds_template(like))


def _to_host_template(tree: Any) -> Any:
    """Concrete host-numpy twin of ``tree``: device arrays come back to
    host, abstract :class:`jax.ShapeDtypeStruct` leaves materialize as
    zeros — a concrete ``item=`` template is the one every orbax version
    accepts (values are overwritten by the checkpoint bytes)."""

    def leaf(x: Any) -> Any:
        if isinstance(x, jax.ShapeDtypeStruct):
            return np.zeros(x.shape, x.dtype)
        if isinstance(x, (jax.Array, np.ndarray)):
            return np.asarray(jax.device_get(x))
        return x

    return jax.tree_util.tree_map(leaf, tree)


def _snapshot_tree(tree: Any) -> Any:
    """Donation-safe snapshot of ``tree`` for an async save — the ONLY
    checkpoint cost the training driver pays on the async path (fault
    site ``ckpt.snapshot``).

    Replicated / host state comes back as the host-numpy template (the
    PR 5 behavior). Sharded (FSDP/TP) state must never host-gather, so
    each jax leaf is copied ON DEVICE instead — same sharding, fresh
    buffers — and blocked until ready, so the caller's next *donating*
    dispatch cannot tear the bytes out from under the background writer
    (orbax then reads only this process's shards from the copy)."""
    _faults.check("ckpt.snapshot")
    if not _is_sharded_tree(tree):
        return _to_host_template(tree)

    def leaf(x: Any) -> Any:
        if isinstance(x, jax.ShapeDtypeStruct):
            return np.zeros(x.shape, x.dtype)
        if isinstance(x, jax.Array):
            return x.copy()
        return x

    snapshot = jax.tree_util.tree_map(leaf, tree)
    jax.block_until_ready(
        [l for l in jax.tree_util.tree_leaves(snapshot)
         if isinstance(l, jax.Array)]
    )
    return snapshot


def _note_background_save(seconds: float) -> None:
    """Book a background writer's wall time with the goodput tracker's
    off-driver ledger (``report()["background"]``) — the async-save
    proof surface: driver-thread ``checkpoint_save`` stays ≈ snapshot
    cost while the real write cost remains observable here."""
    from ..telemetry import goodput as _goodput

    tracker = _goodput.get_goodput_tracker()
    if tracker.enabled:
        tracker.note_background("checkpoint_async_write", seconds)


def _place_into(restored: Any, targets: Any) -> Any:
    """Lay restored host values out like ``targets`` (concrete arrays or
    sharding-carrying ShapeDtypeStructs), refusing silent shape
    mismatches — restoring a (2,) kernel into a (3,) slot must fail
    loudly, not produce a corrupted state. The ONE placement helper for
    both the plain-replicated and elastic restore paths."""

    def _place(r: Any, t: Any) -> Any:
        if not isinstance(t, (jax.Array, jax.ShapeDtypeStruct)):
            return r
        r_arr = np.asarray(r, dtype=t.dtype)
        if r_arr.shape != tuple(t.shape):
            raise ValueError(
                f"checkpoint leaf shape {r_arr.shape} does not match "
                f"expected {tuple(t.shape)}"
            )
        return jax.device_put(r_arr, t.sharding)

    return jax.tree_util.tree_map(_place, restored, targets)


# One warning per checkpoint path per process lifetime (lead process
# only): these fire on every restore of an old checkpoint otherwise, and
# a resuming fleet restores once per process.
_warned_missing_manifest: set[str] = set()
_warned_missing_marker: set[str] = set()


def _warn_once(cache: set[str], path: str, message: str) -> None:
    if jax.process_index() != 0 or path in cache:
        return
    cache.add(path)
    warnings.warn(message, stacklevel=4)


def _restore_elastic(
    path: str,
    like: Any,
    man: dict[str, Any] | None,
    mesh: Any,
    rule: Any,
    root_rank: int,
) -> Any:
    """Explicit elastic restore (``mesh=``/``rule=`` passed): build the
    sharding-carrying template for the CURRENT topology internally —
    from the rule when given, else from the partition specs the manifest
    banked at save time — and land every leaf directly in its new
    layout. Sharded checkpoints reshard through orbax (N→M, no host
    gather); replicated checkpoints take the load-on-root + broadcast
    path and are then placed into the target shardings."""
    if _faults.ARMED:
        _faults.check("elastic.restore")
    if mesh is None:
        from ..runtime import global_mesh

        mesh = global_mesh()
    if man is not None:
        _manifest.check_manifest_shapes(man, like)
    elif rule is None:
        raise ValueError(
            f"elastic restore of {path} without a partition rule needs the "
            f"checkpoint manifest to know the saved partition specs, and "
            f"this checkpoint has none (written before elastic "
            f"checkpoints) — pass rule= for the new topology, or restore "
            f"with a like tree already carrying the target shardings"
        )
    layout = man["layout"] if man is not None else _read_layout_marker(path)
    if layout is None:
        layout = "sharded" if _is_sharded_tree(like) else "replicated"
    template = _manifest.sharded_template(like, man, mesh, rule)
    if layout == "sharded":
        return _restore_sharded(path, template)
    # Replicated checkpoint, explicit target layout: read host bytes via
    # the root-broadcast path (concrete host template: safe on every
    # orbax version, SDS leaves in `like` included), then device_put
    # each leaf into its new sharding — a host→device reshard needs no
    # orbax involvement.
    synced = synchronize(
        _checkpointer().restore(path, item=_to_host_template(like)),
        root_rank=root_rank,
    )
    return _place_into(synced, template)


def save_checkpoint(
    path: str, state: Any, *, force: bool = True, step: int | None = None
) -> None:
    """Write ``state`` (any pytree, e.g. a TrainState) to ``path``.

    Only the lead process writes replicated DP state (identical
    everywhere); sharded FSDP/TP state writes collectively, each process
    its own shards. All processes must call (collective barrier at the end)
    so the flow is SPMD-safe.

    Crash-consistent: bytes land in ``<path>.tmp``, which is renamed to
    ``path``, described by the ``<path>.manifest.json`` topology manifest
    (lead process; the elastic-restore sidecar, see
    :mod:`fluxmpi_tpu.utils.manifest`), and only then committed by the
    fsync'd layout marker — a crash anywhere in between leaves an
    uncommitted directory that discovery skips and
    :class:`CheckpointManager` quarantines at startup, so every committed
    step has its manifest. Transient write failures retry with capped
    exponential backoff (env knobs in the module docstring). ``step``
    (optional) is recorded in the manifest — :class:`CheckpointManager`
    passes its step number.

    Run health: the whole save is attributed to the goodput
    ``checkpoint_save`` bucket when the tracker is enabled (synchronous
    caller-thread saves only — an async background save overlaps
    training and is deliberately not booked as badput).
    """
    with _goodput_segment("checkpoint_save"):
        _save_checkpoint_body(path, state, force=force, step=step)


def _save_checkpoint_body(
    path: str, state: Any, *, force: bool = True, step: int | None = None
) -> None:
    path = os.path.abspath(path)
    layout = "sharded" if _is_sharded_tree(state) else "replicated"
    marker = _layout_marker_path(path)
    tmp = path + ".tmp"
    lead = jax.process_index() == 0
    if not force and (os.path.exists(marker) or os.path.exists(path)):
        # Every process checks (checkpoint storage is shared) so the
        # refusal raises SPMD-consistently — a lead-only raise would
        # strand the other processes at the barrier below.
        raise FileExistsError(
            f"checkpoint already exists at {path} (pass force=True "
            f"to overwrite)"
        )
    shutil.rmtree(tmp, ignore_errors=True)  # stale staging dir
    for stale in glob.glob(glob.escape(tmp) + ".write_failed.*"):
        with contextlib.suppress(OSError):
            os.remove(stale)
    _process_barrier(f"ckpt_preclean:{path}")
    write_exc: BaseException | None = None
    # Per-process retries are only safe when the write attempt has no
    # cross-process coordination inside it — true only in a
    # single-process world (see _with_write_retries).
    collective = jax.process_count() > 1
    try:
        if layout == "sharded":
            _with_write_retries(
                lambda: _save_sharded(tmp, state, True),
                f"sharded checkpoint write to {tmp}",
                collective=collective,
            )
        else:
            # Every process enters the (collective) orbax save — its
            # multihost coordination barriers require all participants;
            # orbax's primary-host logic ensures only the lead process
            # actually writes the replicated bytes.
            host_state = _to_host_template(state)
            _with_write_retries(
                lambda: _checkpointer().save(tmp, host_state, force=True),
                f"checkpoint write to {tmp}",
                collective=collective,
            )
    except (OSError, FaultInjectedError) as exc:
        # Terminal (retry-exhausted) local failure: tell the peers via a
        # sentinel on the shared checkpoint storage BEFORE joining the
        # barrier, so after it every process reads the same failed set
        # and they abort the save together instead of wedging. Best-effort
        # — if even the sentinel cannot land (whole filesystem down),
        # peers fall back to the barrier-wedge diagnostics
        # (_wait_with_diagnostic / FLUXMPI_TPU_CKPT_TIMEOUT).
        write_exc = exc
        with contextlib.suppress(OSError):
            with open(
                f"{tmp}.write_failed.{jax.process_index()}",
                "w",
                encoding="utf-8",
            ) as f:
                f.write(repr(exc))
    _process_barrier(f"ckpt_written:{path}")
    failed = _peer_write_failures(tmp)
    # Every process reads the failed set BEFORE anyone may delete a
    # sentinel: without this barrier a fast aborter's cleanup below could
    # race a slow peer's glob above — the slow peer would see an empty
    # set, take the commit path alone, and decommit the previous
    # committed checkpoint while everyone else aborts.
    _process_barrier(f"ckpt_failcheck:{path}")
    if write_exc is not None or failed:
        # Abort on EVERY process (the sentinels landed before ckpt_written
        # and were read before ckpt_failcheck, so the failed set — and
        # this branch — is agreed), previous committed checkpoint intact:
        # the decommit below never ran. Cleanup is idempotent per process.
        shutil.rmtree(tmp, ignore_errors=True)
        for s in glob.glob(glob.escape(tmp) + ".write_failed.*"):
            with contextlib.suppress(OSError):
                os.remove(s)
        _process_barrier(f"ckpt_abort:{path}")
        if write_exc is not None:
            raise write_exc
        raise OSError(
            f"checkpoint write to {tmp} failed on peer process(es) "
            f"{failed} after retries (see their logs); aborted on all "
            f"processes — the previous committed checkpoint at {path} "
            f"is untouched"
        )
    # Decommit any OLD state at the path only now that the new bytes
    # are fully staged: a failed or crashed write above leaves the
    # previous committed checkpoint untouched. Marker removal comes
    # first so an interrupted cleanup leaves nothing discovery would
    # trust. Every process issues the removals — on the shared storage
    # the concurrent removals are idempotent, and the symmetry keeps the
    # flow SPMD-uniform (no lead/non-lead divergence to coordinate).
    try:
        os.remove(marker)
    except FileNotFoundError:
        pass
    with contextlib.suppress(FileNotFoundError, OSError):
        os.remove(_manifest.manifest_path(path))
    shutil.rmtree(path, ignore_errors=True)
    _process_barrier(f"ckpt_decommit:{path}")  # removals land pre-rename
    # Rename on EVERY process that sees a staging dir: the first rename
    # wins and the rest find the staging dir gone — same SPMD-uniform
    # symmetry as the decommit above, with the race handled explicitly.
    if os.path.isdir(tmp):
        try:
            os.rename(tmp, path)
        except OSError:
            if not os.path.isdir(path):  # lost a shared-storage race: ok
                raise
    # The rename is an entry in the PARENT directory's metadata — make it
    # durable before the marker can declare the step committed (see
    # _fsync_dir: without this a post-return power cut could keep the
    # decommit but lose the rename).
    _fsync_dir(os.path.dirname(path))
    if lead:
        if _faults.ARMED:
            # The crash-between-data-commit-and-manifest window,
            # injectable: the renamed dir exists but carries no manifest
            # (and no marker — still uncommitted, quarantined at startup).
            _faults.check("ckpt.manifest")
        # The topology sidecar rides BEFORE the commit marker so a
        # committed step always has its manifest; built from the original
        # `state` (not the host copy) so sharded leaves keep their specs.
        # A sidecar write failure must NOT abort the save: this runs
        # between barriers on the lead only, so raising here would
        # strand every peer at ckpt_commit — and the checkpoint is
        # complete without it (restore degrades to the topology-blind
        # path with a warning). Only the injected chaos crash
        # propagates: it simulates the process dying, not an I/O error.
        try:
            _manifest.write_manifest(
                path,
                _manifest.build_manifest(state, layout=layout, step=step),
            )
        except (OSError, ValueError) as exc:
            warnings.warn(
                f"could not write the topology manifest beside {path} "
                f"({exc!r}); committing the checkpoint WITHOUT it — "
                f"elastic (cross-topology) restore of this step will "
                f"need an explicit rule, same-topology restore is "
                f"unaffected",
                stacklevel=2,
            )
        # When the installed plan is the layout autotuner's winner, its
        # banked evidence rides next to the manifest (<path>.autotune
        # .json) — best-effort for the same stranded-peer reason.
        try:
            from ..parallel.autotune import write_bank_sidecar

            write_bank_sidecar(path)
        except Exception:
            pass
        if _faults.ARMED:
            # The crash-between-rename-and-commit window, injectable.
            _faults.check("ckpt.commit")
    _process_barrier(f"ckpt_commit:{path}")  # every rename lands first
    if lead:
        _write_layout_marker(path, layout)
    _process_barrier(f"ckpt_save:{path}")


# Sentinel for restore_checkpoint(manifest=...): "not provided — read it
# from disk". Distinct from None, which means "known absent: the caller
# already looked and found no manifest" (train_loop's resume path reads
# the manifest once up front and passes it through, killing the PR 6
# double read+validate per resume).
_MANIFEST_UNREAD = object()


def restore_checkpoint(
    path: str,
    like: Any,
    *,
    root_rank: int = 0,
    allow_layout_change: bool = False,
    mesh: Any = None,
    rule: Any = None,
    parallel: Any = None,
    manifest: Any = _MANIFEST_UNREAD,
) -> Any:
    """Read the checkpoint at ``path`` and return it synchronized from
    ``root_rank`` and laid out like ``like`` (replicated over the mesh).

    The load-on-root-then-broadcast pattern (reference guidance,
    SURVEY.md §5 "Checkpoint/resume"): every process calls this; the root's
    bytes win and land replicated on every device. A sharded ``like``
    (FSDP/TP) instead restores collectively, each leaf landing directly in
    its training sharding — no host gather, no broadcast needed (the
    checkpoint bytes are the single source, so root_rank is moot).

    Elastic restore (docs/fault_tolerance.md, "Elastic resume"): a
    sharded checkpoint restores onto a DIFFERENT mesh topology whenever
    ``like`` carries the target shardings (orbax reshards on read) — and
    with ``mesh=`` (and optionally ``rule=``, a
    :data:`~fluxmpi_tpu.parallel.sharding.Rule`) the target shardings
    are built *internally*: ``like`` only provides structure and global
    shapes (host arrays are fine), the layout comes from the rule or
    from the partition specs the save-time manifest banked, re-validated
    against the new mesh — a leaf the new topology cannot express raises
    :class:`~fluxmpi_tpu.errors.TopologyMismatchError` naming it.
    Crossing the replicated↔sharded *layout family* without an explicit
    ``mesh=``/``rule=`` (e.g. inspecting a pod FSDP checkpoint fully
    replicated on one host) is usually an accident, so the layout marker
    rejects it unless ``allow_layout_change=True``.

    Run health: restore wall time lands in the goodput
    ``checkpoint_restore`` bucket when the tracker is enabled (counted
    once even inside ``train_loop``'s ``resume`` segment — outermost
    attribution wins).

    ``parallel``: a :class:`~fluxmpi_tpu.parallel.ParallelConfig` (or
    resolved plan) in place of ``(mesh=, rule=)`` — the restore target
    is the plan's mesh under the plan's combined partition rule, so the
    SAME declaration that trains a layout also restores into it
    (checkpoint manifests record the saving plan in their ``parallel``
    section). Mutually exclusive with explicit ``mesh=``/``rule=``.

    ``manifest``: a caller that already read+validated the topology
    manifest (``CheckpointManager.read_manifest`` / ``train_loop``'s
    resume bring-up) passes it here — including an explicit ``None``
    for "looked and absent" — so the restore does not read and
    re-validate the sidecar a second time. Left unset, the manifest is
    read from disk as before.
    """
    if parallel is not None:
        if mesh is not None or rule is not None:
            raise ValueError(
                "pass either parallel= (the plan supplies mesh AND rule) "
                "or explicit mesh=/rule=, not both"
            )
        from ..parallel.plan import resolve_parallel

        plan = resolve_parallel(parallel)
        mesh, rule = plan.mesh, plan.rule
    with _goodput_segment("checkpoint_restore"):
        return _restore_checkpoint_body(
            path,
            like,
            root_rank=root_rank,
            allow_layout_change=allow_layout_change,
            mesh=mesh,
            rule=rule,
            manifest=manifest,
        )


def _restore_checkpoint_body(
    path: str,
    like: Any,
    *,
    root_rank: int = 0,
    allow_layout_change: bool = False,
    mesh: Any = None,
    rule: Any = None,
    manifest: Any = _MANIFEST_UNREAD,
) -> Any:
    if _faults.ARMED:
        _faults.check("ckpt.read")
    path = os.path.abspath(path)
    man = (
        _manifest.read_manifest(path)
        if manifest is _MANIFEST_UNREAD
        else manifest
    )
    if man is None:
        _warn_once(
            _warned_missing_manifest,
            path,
            f"checkpoint at {path} has no topology manifest (it predates "
            f"elastic checkpoints); restoring the topology-blind way — "
            f"same-topology restores are unaffected, but a cross-topology "
            f"restore needs the like tree to carry the target shardings",
        )
    if mesh is not None or rule is not None:
        return _restore_elastic(path, like, man, mesh, rule, root_rank)
    if _is_sharded_tree(like):
        if not allow_layout_change:
            _check_layout(path, "sharded")
        elif _read_layout_marker(path) is None:
            _warn_once(
                _warned_missing_marker,
                path,
                f"checkpoint at {path} has no layout marker (it predates "
                f"layout markers, or the save never committed); "
                f"allow_layout_change=True cannot tell an old checkpoint "
                f"from a wrong-family one here — verify the source run",
            )
        if man is not None:
            _manifest.check_manifest_shapes(man, like)
        return _restore_sharded(path, like)
    if not allow_layout_change:
        _check_layout(path, "replicated")
    elif _read_layout_marker(path) is None:
        _warn_once(
            _warned_missing_marker,
            path,
            f"checkpoint at {path} has no layout marker (it predates "
            f"layout markers, or the save never committed); "
            f"allow_layout_change=True cannot tell an old checkpoint "
            f"from a wrong-family one here — verify the source run",
        )
    if man is not None:
        _manifest.check_manifest_shapes(man, like)
    # The restore template only needs structure/shape/dtype — avoid pulling
    # the whole live state to host just to describe it.
    try:
        restored = _checkpointer().restore(path, item=_sds_template(like))
    except (TypeError, ValueError) as exc:
        if allow_layout_change:
            # The sharding-carrying template IS the safety mechanism of the
            # cross-family elastic restore; a bare host-array fallback would
            # let orbax consult the checkpoint's saved sharding file (save
            # topology's devices). Fail loudly instead of degrading.
            raise RuntimeError(
                "elastic cross-family restore needs an orbax version that "
                "accepts sharding-carrying ShapeDtypeStruct templates"
            ) from exc
        # Older orbax versions reject ShapeDtypeStruct templates; fall back to
        # a concrete-host-array template (same-topology restores only reach
        # here). Genuine restore errors (missing or corrupt checkpoint) raise
        # other exception types and propagate.
        restored = _checkpointer().restore(path, item=_to_host_template(like))
    # Match leaf types/placement of `like` (replicated jax arrays) via the
    # shared shape-refusing placement helper.
    return _place_into(synchronize(restored, root_rank=root_rank), like)


_STEP_DIR_RE = re.compile(r"^step_(\d{8})$")


def _gather_steps(step: int) -> np.ndarray | None:
    """Every process's view of the step about to be saved (``None`` =
    single-process world, nothing to compare). ONE cheap host allgather
    on the caller thread — never a device collective, never on the
    background save thread (submission-order inversion, see
    :func:`_process_barrier`). Module-level so chaos tests can
    monkeypatch a desynced world."""
    if jax.process_count() == 1:
        return None
    from ..comm import host_allgather  # pragma: no cover - multihost only

    return host_allgather(np.asarray(step, np.int64))


class CheckpointManager:
    """Training-run checkpoint lifecycle on top of
    :func:`save_checkpoint`/:func:`restore_checkpoint` (VERDICT r2 next #7;
    the reference leaves all of this user-land, SURVEY.md §5
    "checkpoint/resume": ABSENT):

    - **step-numbered directories** ``<dir>/step_00000042`` — the layout
      marker the core writes *after* a save completes doubles as the commit
      marker, so a torn save is never listed as restorable;
    - **keep-k retention** (``max_to_keep``), oldest deleted after each
      successful save, lead process only;
    - **async save** (``async_save=True`` / ``FLUXMPI_TPU_CKPT_ASYNC``,
      per-call ``save(async_=...)``): the state is snapshotted up front
      (donation-safe — replicated state to host, sharded state copied on
      device, fault site ``ckpt.snapshot``), then a single background
      writer thread runs the full crash-consistent commit protocol
      (fault site ``ckpt.async_write``). The driver never blocks past
      the snapshot: overlapping saves **coalesce** — at most one write
      is in flight, and a newer request supersedes any queued one
      (``checkpoint.async_superseded``); a background failure is stored
      and re-raised at the next ``save``/``wait_until_finished``/
      ``restore``/``close`` — it can never strand peers mid-protocol
      beyond what the peer-sentinel abort already handles, and it never
      corrupts the last committed step. :meth:`wait_until_finished`
      joins;
    - **multi-tier retention** (``local_dir=`` /
      ``FLUXMPI_TPU_CKPT_LOCAL_DIR``): saves commit to a local-disk
      fast tier first, then a background **promotion** copies the
      committed artifacts to the durable ``directory`` with the same
      rename→manifest→marker ordering; the two tiers retain
      independently (``local_max_to_keep`` / ``max_to_keep``) and
      discovery/restore prefer the fastest tier holding a committed
      step. Single-controller worlds only: per-host local disks would
      break the shared-storage contract the multi-process commit
      protocol relies on, so multi-process runs warn once and use the
      durable tier alone;
    - **resume discovery**: :meth:`latest_step` / :meth:`restore` with
      ``step=None`` find the newest complete checkpoint;
    - **partial quarantine**: startup sweeps the directory for
      uncommitted step dirs and stale ``.tmp`` staging dirs (a crash
      mid-save) and moves them into ``_quarantine/`` — they are already
      invisible to discovery, but leaving them in place would let a
      torn tree shadow a later save of the same step;
    - **step-agreement guard**: before each save one cheap
      :func:`~fluxmpi_tpu.comm.host_allgather` asserts every process is
      checkpointing the SAME step; on desync the save aborts with
      :class:`~fluxmpi_tpu.errors.CheckpointDesyncError` and the
      collective flight-recorder tail is dumped beside the directory —
      a mixed-step "checkpoint" is corruption, not a checkpoint.

    All methods must be called on every process (saves/restores of sharded
    state are collective).
    """

    def __init__(
        self,
        directory: str,
        *,
        max_to_keep: int | None = 3,
        async_save: bool | None = None,
        local_dir: str | None = None,
        local_max_to_keep: int | None = 2,
    ):
        self.directory = os.path.abspath(directory)
        self.max_to_keep = max_to_keep
        if async_save is None:
            async_save = os.environ.get(_ENV_ASYNC, "") != "0"
        self._async = bool(async_save)
        if local_dir is None:
            local_dir = os.environ.get(_ENV_LOCAL_DIR) or None
        if local_dir is not None and jax.process_count() > 1:
            # Per-host local disks break the shared-storage contract the
            # multi-process commit protocol (lead-only marker, peer
            # sentinels, discovery) relies on.
            warnings.warn(
                "CheckpointManager local_dir fast tier is single-"
                "controller only; multi-process runs use the durable "
                "tier alone",
                stacklevel=2,
            )
            local_dir = None
        self.local_dir = (
            os.path.abspath(local_dir) if local_dir is not None else None
        )
        self.local_max_to_keep = local_max_to_keep
        os.makedirs(self.directory, exist_ok=True)
        self.quarantined = self._quarantine_partials()
        if self.local_dir is not None:
            os.makedirs(self.local_dir, exist_ok=True)
            self.quarantined += self._quarantine_partials(self.local_dir)
        self._executor: ThreadPoolExecutor | None = None
        # Async coalescing state, all under _lock: the in-flight writer
        # future (its writer drains _queued before completing, so ONE
        # wait covers every accepted request), the one queued (step,
        # snapshot, force) slot a newer request supersedes, and the
        # stored failure of a finished background write.
        self._inflight: Future | None = None
        self._queued: tuple[int, Any, bool] | None = None
        self._async_error: BaseException | None = None
        self.superseded = 0
        self._inflight_step: int | None = None
        self._inflight_since: float | None = None
        self._last_committed: tuple[int, str] | None = None
        self._lock = threading.Lock()

    def _quarantine_partials(self, directory: str | None = None) -> list[str]:
        """Move uncommitted step dirs / stale staging dirs into
        ``_quarantine/`` (lead process; barrier'd so no peer races a
        restore against the sweep). Returns the quarantined names."""
        directory = self.directory if directory is None else directory
        moved: list[str] = []
        removed: list[str] = []
        if jax.process_index() == 0:
            qdir = os.path.join(directory, "_quarantine")
            for name in sorted(os.listdir(directory)):
                full = os.path.join(directory, name)
                if not os.path.exists(full):
                    # Moved along with its step dir earlier this sweep
                    # (a partial dir's manifest sibling).
                    continue
                partial = os.path.isdir(full) and (
                    name.endswith(".tmp")
                    or (
                        _STEP_DIR_RE.match(name)
                        and _read_layout_marker(full) is None
                    )
                )
                orphan_marker = (
                    name.endswith(".fluxmpi_layout")
                    and not os.path.isdir(full[: -len(".fluxmpi_layout")])
                )
                orphan_manifest = (
                    name.endswith(".manifest.json")
                    and not os.path.isdir(full[: -len(".manifest.json")])
                )
                if orphan_marker or orphan_manifest:
                    # A marker/manifest whose directory vanished (crash
                    # mid-retention): committed-looking but unrestorable.
                    os.remove(full)
                    removed.append(name)
                    continue
                if not partial:
                    continue
                os.makedirs(qdir, exist_ok=True)
                target = os.path.join(qdir, name)
                suffix = 0
                while os.path.exists(target):
                    suffix += 1
                    target = os.path.join(qdir, f"{name}.{suffix}")
                os.rename(full, target)
                moved.append(name)
                # A crash in the manifest→marker window leaves the
                # uncommitted dir WITH its manifest — the sidecar belongs
                # to the quarantined artifact, so it moves along quietly
                # (it is part of `name`, not a separate finding).
                sibling = _manifest.manifest_path(full)
                if os.path.exists(sibling):
                    os.rename(sibling, target + ".manifest.json")
            if moved or removed:
                parts = []
                if moved:
                    parts.append(
                        f"quarantined {len(moved)} partial checkpoint "
                        f"artifact(s) under {qdir}: {moved}"
                    )
                if removed:
                    parts.append(
                        f"removed {len(removed)} orphan commit-marker/"
                        f"manifest file(s): {removed}"
                    )
                warnings.warn(
                    "; ".join(parts) + " — a previous run crashed "
                    "mid-save; the newest COMMITTED step is unaffected",
                    stacklevel=3,
                )
        _process_barrier(f"ckpt_quarantine:{directory}")
        return moved + removed

    def _check_step_agreement(self, step: int) -> None:
        gathered = _gather_steps(step)
        if gathered is None or bool((gathered == gathered.flat[0]).all()):
            return
        from ..telemetry.flight_recorder import get_flight_recorder

        dump_path = os.path.join(
            self.directory,
            f"ckpt_desync_flight.{jax.process_index()}.json",
        )
        try:
            with open(dump_path, "w", encoding="utf-8") as f:
                json.dump(get_flight_recorder().dump(), f, indent=1)
        except Exception:  # the abort matters more than the dump
            dump_path = "<flight dump failed>"
        raise CheckpointDesyncError(
            f"processes disagree on the checkpoint step: "
            f"{np.asarray(gathered).ravel().tolist()} — aborting the save "
            f"instead of banking a mixed-step checkpoint; flight-recorder "
            f"context written to {dump_path} (diff per-host dumps with "
            f"fluxmpi_tpu.telemetry.diff_flight_dumps to localize the "
            f"desync)"
        )

    def _step_path(self, step: int, directory: str | None = None) -> str:
        return os.path.join(
            self.directory if directory is None else directory,
            f"step_{step:08d}",
        )

    @staticmethod
    def _steps_in(directory: str) -> list[int]:
        steps = []
        try:
            names = os.listdir(directory)
        except FileNotFoundError:
            return []
        for name in names:
            m = _STEP_DIR_RE.match(name)
            if m and _read_layout_marker(
                os.path.join(directory, name)
            ) is not None:
                steps.append(int(m.group(1)))
        return sorted(steps)

    def all_steps(self) -> list[int]:
        """Steps with *complete* checkpoints (layout marker present) in
        ANY tier, ascending — a step committed locally but not yet
        promoted is restorable and counts."""
        steps = set(self._steps_in(self.directory))
        if self.local_dir is not None:
            steps |= set(self._steps_in(self.local_dir))
        return sorted(steps)

    def latest_step(self) -> int | None:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def tier_of(self, step: int) -> str | None:
        """Which tier a restore of ``step`` would read: ``"local"``
        (fast tier holds the committed step) beats ``"durable"``; None
        when no tier has it committed."""
        if self.local_dir is not None and _read_layout_marker(
            self._step_path(step, self.local_dir)
        ) is not None:
            return "local"
        if _read_layout_marker(self._step_path(step)) is not None:
            return "durable"
        return None

    def _tier_path(self, step: int) -> str:
        """The restore path for ``step``: the fastest tier holding a
        committed copy (restore-side of the multi-tier contract)."""
        if self.tier_of(step) == "local":
            return self._step_path(step, self.local_dir)
        return self._step_path(step)

    def _raise_async_error(self) -> None:
        with self._lock:
            err, self._async_error = self._async_error, None
        if err is not None:
            raise err

    def save(
        self,
        step: int,
        state: Any,
        *,
        force: bool = True,
        async_: bool | None = None,
    ) -> None:
        """Checkpoint ``state`` as ``step``.

        ``async_`` (default: the manager's ``async_save`` setting) picks
        the path. **Async**: the driver pays ONLY the donation-safe
        snapshot (replicated state to host, sharded state copied on
        device — fault site ``ckpt.snapshot``) and returns; a single
        background writer runs the crash-consistent commit protocol
        (fault site ``ckpt.async_write``). Overlapping requests
        coalesce: at most one write is in flight, a newer request
        supersedes any queued one (its snapshot is dropped, counted in
        ``checkpoint.async_superseded``), and a stored background
        failure is re-raised here before a new snapshot is taken.
        **Sync** (``async_=False``): joins any in-flight write, then
        saves inline.

        Aborts with :class:`~fluxmpi_tpu.errors.CheckpointDesyncError`
        (flight-recorder context dumped) when processes disagree on
        ``step`` — checked on the caller thread, before any bytes move.

        Goodput: the caller-thread cost — agreement check, snapshot,
        and sync saves — books into the ``checkpoint_save`` bucket; the
        background write overlaps training and books into the tracker's
        off-driver ``background`` ledger instead (the async zero-
        downtime proof: driver bucket ≈ snapshot cost)."""
        use_async = self._async if async_ is None else bool(async_)
        with _goodput_segment("checkpoint_save"):
            self._raise_async_error()
            self._check_step_agreement(step)
            if not use_async:
                self.wait_until_finished()
                self._save_and_retain(step, state, force)
                return
            snapshot = _snapshot_tree(state)
            with self._lock:
                if self._executor is None:
                    self._executor = ThreadPoolExecutor(
                        max_workers=1, thread_name_prefix="ckpt"
                    )
                if self._inflight is not None:
                    # Coalesce: the writer is busy — park this request
                    # in the one queued slot, superseding whatever sat
                    # there (the writer drains the slot before its
                    # future completes, so no separate wait is needed).
                    if self._queued is not None:
                        self.superseded += 1
                        self._count("checkpoint.async_superseded")
                    self._queued = (step, snapshot, force)
                else:
                    self._inflight_step = step
                    self._inflight_since = time.time()
                    self._inflight = self._executor.submit(
                        self._async_writer, step, snapshot, force
                    )
                self._count("checkpoint.async_saves")
        self._note_board()

    def _async_writer(self, step: int, state: Any, force: bool) -> None:
        """Background writer: run the commit protocol for the submitted
        snapshot, then drain the queued slot until it is empty. Never
        raises — a failure is stored for the next driver-thread entry
        point (and any queued request is dropped with it: its snapshot
        was taken under assumptions the failure may have broken)."""
        while True:
            t0 = time.perf_counter()
            try:
                _faults.check("ckpt.async_write")
                self._save_and_retain(step, state, force)
            except BaseException as exc:
                with self._lock:
                    self._async_error = exc
                    self._queued = None
                    self._inflight = None
                    self._inflight_step = None
                    self._inflight_since = None
                return
            finally:
                _note_background_save(time.perf_counter() - t0)
            with self._lock:
                if self._queued is None:
                    self._inflight = None
                    self._inflight_step = None
                    self._inflight_since = None
                    return
                step, state, force = self._queued
                self._queued = None
                self._inflight_step = step
                self._inflight_since = time.time()

    def _retain(self, directory: str, keep_k: int | None, step: int) -> None:
        if keep_k is None:
            return
        steps = self._steps_in(directory)
        keep = set(steps[-keep_k:])
        keep.add(step)
        if jax.process_index() == 0:
            for s in steps:
                if s not in keep:
                    path = self._step_path(s, directory)
                    # Marker first: once it is gone the step is
                    # invisible to discovery even if the rmtree below
                    # is interrupted (the startup sweep then collects
                    # the leftover dir and manifest).
                    try:
                        os.remove(_layout_marker_path(path))
                    except FileNotFoundError:
                        pass
                    with contextlib.suppress(FileNotFoundError, OSError):
                        os.remove(_manifest.manifest_path(path))
                    shutil.rmtree(path, ignore_errors=True)

    def _save_and_retain(self, step: int, state: Any, force: bool) -> None:
        if self.local_dir is None:
            save_checkpoint(
                self._step_path(step), state, force=force, step=step
            )
            self._retain(self.directory, self.max_to_keep, step)
            self._set_committed(step, "durable")
            return
        # Fast tier first: the step is restorable the moment the local
        # commit lands; promotion to durable storage rides the same
        # (background, under async) writer afterwards.
        save_checkpoint(
            self._step_path(step, self.local_dir), state,
            force=force, step=step,
        )
        self._retain(self.local_dir, self.local_max_to_keep, step)
        self._set_committed(step, "local")
        self._promote(step)
        self._retain(self.directory, self.max_to_keep, step)

    def _promote(self, step: int) -> None:
        """Copy the locally-committed ``step`` into the durable tier
        with the commit protocol's ordering (stage → rename → manifest →
        marker), so a crash mid-promotion leaves the durable tier with
        either the previous committed copy or none — never a torn one."""
        src = self._step_path(step, self.local_dir)
        dst = self._step_path(step)
        tmp = dst + ".tmp"
        if os.path.exists(tmp):
            shutil.rmtree(tmp, ignore_errors=True)
        shutil.copytree(src, tmp)
        if os.path.exists(dst):
            try:
                os.remove(_layout_marker_path(dst))
            except FileNotFoundError:
                pass
            with contextlib.suppress(FileNotFoundError, OSError):
                os.remove(_manifest.manifest_path(dst))
            shutil.rmtree(dst, ignore_errors=True)
        os.rename(tmp, dst)
        _fsync_dir(os.path.dirname(dst))
        src_manifest = _manifest.manifest_path(src)
        if os.path.exists(src_manifest):
            shutil.copyfile(src_manifest, _manifest.manifest_path(dst))
        for sidecar in glob.glob(src + ".autotune.json"):
            shutil.copyfile(sidecar, dst + ".autotune.json")
        _write_layout_marker(dst, _read_layout_marker(src) or "replicated")
        self._count("checkpoint.promotions")

    def _count(self, name: str) -> None:
        registry = _telemetry_registry()
        if registry is not None and getattr(registry, "enabled", True):
            registry.counter(name).inc()

    def _set_committed(self, step: int, tier: str) -> None:
        with self._lock:
            self._last_committed = (step, tier)
        self._note_board()

    def _note_board(self) -> None:
        """Post the CHECKPOINT board to the live exporter (when one is
        serving): last committed step + tier, and the in-flight async
        save's step/age. A dict merge under the exporter's lock — the
        zero-cost-when-off contract: no exporter, no calls."""
        from ..telemetry import export as _export

        exporter = _export.get_exporter()
        if exporter is None or not exporter.enabled:
            return
        with self._lock:
            committed = self._last_committed
            fields: dict[str, Any] = {
                "last_committed_step": committed[0] if committed else None,
                "tier": committed[1] if committed else None,
                "async": self._async,
                "inflight_step": self._inflight_step,
                "inflight_since_unix": self._inflight_since,
                "superseded": self.superseded,
            }
        exporter.note_checkpoint(**fields)

    def wait_until_finished(self) -> None:
        """Block until any in-flight async save (queued requests
        included — the writer drains them under the same future) has
        committed; re-raises a stored background failure. The wait is
        host time spent on checkpointing — goodput ``checkpoint_save``
        badput (no-op booking when nothing is pending or the tracker is
        off)."""
        while True:
            with self._lock:
                pending = self._inflight
            if pending is None:
                break
            with _goodput_segment("checkpoint_save"):
                _wait_with_diagnostic(
                    pending, "in-flight async checkpoint save"
                )
            with self._lock:
                if self._inflight is pending:
                    # The writer clears this itself on its way out; a
                    # future that failed at submission time would spin
                    # here forever without the fallback clear.
                    self._inflight = None
        self._raise_async_error()

    def read_manifest(self, step: int | None = None) -> dict[str, Any] | None:
        """The topology manifest of ``step`` (default: latest complete
        checkpoint), or None when the step has no valid manifest — a
        checkpoint written before elastic checkpoints, or nothing saved
        yet. See :mod:`fluxmpi_tpu.utils.manifest`."""
        self.wait_until_finished()
        if step is None:
            step = self.latest_step()
            if step is None:
                return None
        return _manifest.read_manifest(self._tier_path(step))

    def restore(
        self,
        like: Any,
        *,
        step: int | None = None,
        allow_layout_change: bool = False,
        mesh: Any = None,
        rule: Any = None,
        parallel: Any = None,
        manifest: Any = _MANIFEST_UNREAD,
    ) -> tuple[int, Any]:
        """Restore ``step`` (default: latest complete) as
        ``(step, state)``; raises ``FileNotFoundError`` when nothing is
        restorable. ``allow_layout_change``, ``mesh``, ``rule``,
        ``parallel`` (a ParallelConfig/plan in place of mesh+rule) and
        ``manifest`` (a sidecar the caller already read via
        :meth:`read_manifest` — skips the second read+validate) forward
        to :func:`restore_checkpoint` (elastic cross-family /
        cross-topology restore)."""
        self.wait_until_finished()
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no complete checkpoint under {self.directory}"
                )
        return step, restore_checkpoint(
            self._tier_path(step), like,
            allow_layout_change=allow_layout_change,
            mesh=mesh, rule=rule, parallel=parallel, manifest=manifest,
        )

    def close(self) -> None:
        try:
            self.wait_until_finished()
        finally:
            if self._executor is not None:
                self._executor.shutdown(wait=True)

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
