"""Checkpoint / resume.

The reference has no checkpoint subsystem; its enabling primitive is
``synchronize!`` — load state on the root rank, broadcast to all
(SURVEY.md §5; reference src/synchronize.jl). Here that pattern becomes a
first-class pair: :func:`save_checkpoint` writes the (replicated) train
state from the lead process via orbax; :func:`restore_checkpoint` reads it
and re-synchronizes/replicates it over the mesh — the exact
load-on-root-then-broadcast flow, one call.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import numpy as np

from ..sync import synchronize

__all__ = ["save_checkpoint", "restore_checkpoint"]


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def save_checkpoint(path: str, state: Any, *, force: bool = True) -> None:
    """Write ``state`` (any pytree, e.g. a TrainState) to ``path``.

    Only the lead process writes (replicated DP state is identical
    everywhere); all processes must call (collective barrier at the end) so
    the flow is SPMD-safe.
    """
    path = os.path.abspath(path)
    if jax.process_index() == 0:
        # Only the writer pays the device→host transfer; replicated DP
        # state is identical on every process.
        host_state = jax.tree_util.tree_map(
            lambda x: np.asarray(jax.device_get(x))
            if isinstance(x, (jax.Array, np.ndarray))
            else x,
            state,
        )
        _checkpointer().save(path, host_state, force=force)
    if jax.process_count() > 1:  # pragma: no cover - multihost only
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"ckpt_save:{path}")


def restore_checkpoint(path: str, like: Any, *, root_rank: int = 0) -> Any:
    """Read the checkpoint at ``path`` and return it synchronized from
    ``root_rank`` and laid out like ``like`` (replicated over the mesh).

    The load-on-root-then-broadcast pattern (reference guidance,
    SURVEY.md §5 "Checkpoint/resume"): every process calls this; the root's
    bytes win and land replicated on every device.
    """
    path = os.path.abspath(path)
    # The restore template only needs structure/shape/dtype — avoid pulling
    # the whole live state to host just to describe it.
    try:
        template = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)
            if isinstance(x, jax.Array)
            else x,
            like,
        )
        restored = _checkpointer().restore(path, item=template)
    except (TypeError, ValueError):
        # Older orbax versions reject ShapeDtypeStruct templates; fall back to
        # a concrete-host-array template. Genuine restore errors (missing or
        # corrupt checkpoint) raise other exception types and propagate.
        restored = _checkpointer().restore(
            path,
            item=jax.tree_util.tree_map(
                lambda x: np.asarray(jax.device_get(x))
                if isinstance(x, (jax.Array, np.ndarray))
                else x,
                like,
            ),
        )
    synced = synchronize(restored, root_rank=root_rank)

    # Match leaf types/placement of `like` (replicated jax arrays), refusing
    # silent shape mismatches — restoring a (2,) kernel into a (3,) slot
    # must fail loudly, not produce a corrupted state.
    def _place(r, l):
        if isinstance(l, jax.Array):
            r_arr = jax.numpy.asarray(r, dtype=l.dtype)
            if r_arr.shape != l.shape:
                raise ValueError(
                    f"checkpoint leaf shape {r_arr.shape} does not match "
                    f"expected {l.shape}"
                )
            return jax.device_put(r_arr, l.sharding)
        return r

    return jax.tree_util.tree_map(_place, synced, like)
