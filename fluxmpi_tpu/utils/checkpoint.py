"""Checkpoint / resume.

The reference has no checkpoint subsystem; its enabling primitive is
``synchronize!`` — load state on the root rank, broadcast to all
(SURVEY.md §5; reference src/synchronize.jl). Here that pattern becomes a
first-class pair with two layouts handled transparently:

- **Replicated** state (plain DP): :func:`save_checkpoint` writes from the
  lead process via orbax; :func:`restore_checkpoint` reads it and
  re-synchronizes/replicates over the mesh — the exact
  load-on-root-then-broadcast flow, one call.
- **Sharded** state (FSDP/TP layouts from
  :mod:`fluxmpi_tpu.parallel.sharding`): saved and restored through orbax's
  sharding-aware ``StandardCheckpointer`` — every process writes/reads only
  its own shards, and restore lands each leaf directly in its training
  ``NamedSharding``; the state never gathers onto one host (VERDICT r1
  weak #5).
"""

from __future__ import annotations

import os
from typing import Any

import jax
import numpy as np

from ..sync import synchronize

__all__ = ["save_checkpoint", "restore_checkpoint"]


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def _is_sharded_tree(tree: Any) -> bool:
    """True when any leaf is laid out non-replicated over >1 device (an
    FSDP/TP state) — the layouts that must never host-gather."""
    return any(
        isinstance(l, jax.Array)
        and len(l.sharding.device_set) > 1
        and not l.is_fully_replicated
        for l in jax.tree_util.tree_leaves(tree)
    )


def _layout_marker_path(path: str) -> str:
    # A sibling of the checkpoint directory, never inside it: orbax
    # interprets directory contents as checkpoint tree entries.
    return path.rstrip(os.sep) + ".fluxmpi_layout"


def _write_layout_marker(path: str, layout: str) -> None:
    if jax.process_index() == 0:
        with open(_layout_marker_path(path), "w") as f:
            f.write(layout)


def _read_layout_marker(path: str) -> str | None:
    marker = _layout_marker_path(path)
    if os.path.exists(marker):
        with open(marker) as f:
            return f.read().strip()
    return None


def _check_layout(path: str, expected: str) -> None:
    saved = _read_layout_marker(path)
    if saved is not None and saved != expected:
        raise ValueError(
            f"checkpoint at {path} was saved with {saved} layout but the "
            f"restore template is {expected}: restoring a sharded (FSDP/TP) "
            "checkpoint needs a `like` tree carrying the training shardings "
            "(and vice versa) — re-shard the template with shard_tree, or "
            "re-save in the target layout"
        )


def _save_sharded(path: str, state: Any, force: bool) -> None:
    import orbax.checkpoint as ocp

    # orbax's own force handles primary-host deletion behind cross-process
    # barriers — no hand-rolled rmtree (which would race non-zero ranks
    # into save()'s exists-check).
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, state, force=force)
    ckptr.wait_until_finished()


def _restore_sharded(path: str, like: Any) -> Any:
    import orbax.checkpoint as ocp

    template = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        if isinstance(x, jax.Array)
        else x,
        like,
    )
    return ocp.StandardCheckpointer().restore(path, template)


def save_checkpoint(path: str, state: Any, *, force: bool = True) -> None:
    """Write ``state`` (any pytree, e.g. a TrainState) to ``path``.

    Only the lead process writes replicated DP state (identical
    everywhere); sharded FSDP/TP state writes collectively, each process
    its own shards. All processes must call (collective barrier at the end)
    so the flow is SPMD-safe.
    """
    path = os.path.abspath(path)
    if _is_sharded_tree(state):
        _save_sharded(path, state, force)
        _write_layout_marker(path, "sharded")
    else:
        # Every process enters the (collective) orbax save — its multihost
        # coordination barriers require all participants; orbax's
        # primary-host logic ensures only the lead process actually writes
        # the replicated bytes.
        host_state = jax.tree_util.tree_map(
            lambda x: np.asarray(jax.device_get(x))
            if isinstance(x, (jax.Array, np.ndarray))
            else x,
            state,
        )
        _checkpointer().save(path, host_state, force=force)
        _write_layout_marker(path, "replicated")
    if jax.process_count() > 1:  # pragma: no cover - multihost only
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"ckpt_save:{path}")


def restore_checkpoint(path: str, like: Any, *, root_rank: int = 0) -> Any:
    """Read the checkpoint at ``path`` and return it synchronized from
    ``root_rank`` and laid out like ``like`` (replicated over the mesh).

    The load-on-root-then-broadcast pattern (reference guidance,
    SURVEY.md §5 "Checkpoint/resume"): every process calls this; the root's
    bytes win and land replicated on every device. A sharded ``like``
    (FSDP/TP) instead restores collectively, each leaf landing directly in
    its training sharding — no host gather, no broadcast needed (the
    checkpoint bytes are the single source, so root_rank is moot).
    """
    path = os.path.abspath(path)
    if _is_sharded_tree(like):
        _check_layout(path, "sharded")
        return _restore_sharded(path, like)
    _check_layout(path, "replicated")
    # The restore template only needs structure/shape/dtype — avoid pulling
    # the whole live state to host just to describe it.
    try:
        template = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)
            if isinstance(x, jax.Array)
            else x,
            like,
        )
        restored = _checkpointer().restore(path, item=template)
    except (TypeError, ValueError):
        # Older orbax versions reject ShapeDtypeStruct templates; fall back to
        # a concrete-host-array template. Genuine restore errors (missing or
        # corrupt checkpoint) raise other exception types and propagate.
        restored = _checkpointer().restore(
            path,
            item=jax.tree_util.tree_map(
                lambda x: np.asarray(jax.device_get(x))
                if isinstance(x, (jax.Array, np.ndarray))
                else x,
                like,
            ),
        )
    synced = synchronize(restored, root_rank=root_rank)

    # Match leaf types/placement of `like` (replicated jax arrays), refusing
    # silent shape mismatches — restoring a (2,) kernel into a (3,) slot
    # must fail loudly, not produce a corrupted state.
    def _place(r, l):
        if isinstance(l, jax.Array):
            r_arr = jax.numpy.asarray(r, dtype=l.dtype)
            if r_arr.shape != l.shape:
                raise ValueError(
                    f"checkpoint leaf shape {r_arr.shape} does not match "
                    f"expected {l.shape}"
                )
            return jax.device_put(r_arr, l.sharding)
        return r

    return jax.tree_util.tree_map(_place, synced, like)
