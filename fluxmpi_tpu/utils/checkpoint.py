"""Checkpoint / resume.

The reference has no checkpoint subsystem; its enabling primitive is
``synchronize!`` — load state on the root rank, broadcast to all
(SURVEY.md §5; reference src/synchronize.jl). Here that pattern becomes a
first-class pair with two layouts handled transparently:

- **Replicated** state (plain DP): :func:`save_checkpoint` writes from the
  lead process via orbax; :func:`restore_checkpoint` reads it and
  re-synchronizes/replicates over the mesh — the exact
  load-on-root-then-broadcast flow, one call.
- **Sharded** state (FSDP/TP layouts from
  :mod:`fluxmpi_tpu.parallel.sharding`): saved and restored through orbax's
  sharding-aware ``StandardCheckpointer`` — every process writes/reads only
  its own shards, and restore lands each leaf directly in its training
  ``NamedSharding``; the state never gathers onto one host (VERDICT r1
  weak #5).
"""

from __future__ import annotations

import os
import re
import shutil
import threading
import warnings
from concurrent.futures import Future, ThreadPoolExecutor
from concurrent.futures import TimeoutError as _FutureTimeout
from typing import Any

import jax
import numpy as np

from ..sync import synchronize

__all__ = ["save_checkpoint", "restore_checkpoint", "CheckpointManager"]


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def _wait_with_diagnostic(
    fut: Future, what: str, warn_after_s: float = 60.0
) -> None:
    """``fut.result()`` that surfaces a wedge instead of hanging silently:
    a background save that never completes (e.g. one process missing a
    cross-process barrier) cannot be forced to finish, but the periodic
    warning turns an inexplicable hang into a diagnosable one (ADVICE r3)."""
    waited = 0.0
    while True:
        try:
            fut.result(timeout=warn_after_s)
            return
        except _FutureTimeout:
            waited += warn_after_s
            warnings.warn(
                f"{what} has not completed after {waited:.0f}s — possible "
                f"cross-process barrier wedge (a peer process may have "
                f"exited or diverged); still waiting",
                stacklevel=2,
            )


def _process_barrier(name: str) -> None:
    """Cross-process barrier over the coordination service — NOT a device
    collective. CheckpointManager runs saves on a background thread; a
    device collective there could be submitted in a different order than
    the main thread's train-step collectives on another process, and JAX
    multi-controller deadlocks on submission-order inversion. The
    coordination-service barrier has no device program, so thread timing
    cannot invert anything."""
    if jax.process_count() <= 1:
        return
    try:  # pragma: no cover - multihost only
        from orbax.checkpoint import multihost

        multihost.sync_global_processes(name)
    except Exception:  # pragma: no cover - very old orbax
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


def _is_sharded_tree(tree: Any) -> bool:
    """True when any leaf is laid out non-replicated over >1 device (an
    FSDP/TP state) — the layouts that must never host-gather."""
    return any(
        isinstance(l, jax.Array)
        and len(l.sharding.device_set) > 1
        and not l.is_fully_replicated
        for l in jax.tree_util.tree_leaves(tree)
    )


def _layout_marker_path(path: str) -> str:
    # A sibling of the checkpoint directory, never inside it: orbax
    # interprets directory contents as checkpoint tree entries.
    return path.rstrip(os.sep) + ".fluxmpi_layout"


def _write_layout_marker(path: str, layout: str) -> None:
    if jax.process_index() == 0:
        with open(_layout_marker_path(path), "w") as f:
            f.write(layout)


def _read_layout_marker(path: str) -> str | None:
    marker = _layout_marker_path(path)
    if os.path.exists(marker):
        with open(marker) as f:
            return f.read().strip()
    return None


def _check_layout(path: str, expected: str) -> None:
    saved = _read_layout_marker(path)
    if saved is not None and saved != expected:
        raise ValueError(
            f"checkpoint at {path} was saved with {saved} layout but the "
            f"restore template is {expected}: restoring a sharded (FSDP/TP) "
            "checkpoint needs a `like` tree carrying the training shardings "
            "(and vice versa) — re-shard the template with shard_tree, or "
            "pass allow_layout_change=True to cross layout families "
            "deliberately"
        )


def _save_sharded(path: str, state: Any, force: bool) -> None:
    import orbax.checkpoint as ocp

    # orbax's own force handles primary-host deletion behind cross-process
    # barriers — no hand-rolled rmtree (which would race non-zero ranks
    # into save()'s exists-check).
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, state, force=force)
    ckptr.wait_until_finished()


def _sds_template(like: Any) -> Any:
    """Restore template carrying the TARGET's sharding on every jax leaf —
    orbax then reshards to it deterministically instead of consulting the
    checkpoint's saved sharding file (which references the SAVE topology's
    devices and is unsafe to apply on a different one)."""
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        if isinstance(x, jax.Array)
        else x,
        like,
    )


def _restore_sharded(path: str, like: Any) -> Any:
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer().restore(path, _sds_template(like))


def save_checkpoint(path: str, state: Any, *, force: bool = True) -> None:
    """Write ``state`` (any pytree, e.g. a TrainState) to ``path``.

    Only the lead process writes replicated DP state (identical
    everywhere); sharded FSDP/TP state writes collectively, each process
    its own shards. All processes must call (collective barrier at the end)
    so the flow is SPMD-safe.
    """
    path = os.path.abspath(path)
    if _is_sharded_tree(state):
        _save_sharded(path, state, force)
        _write_layout_marker(path, "sharded")
    else:
        # Every process enters the (collective) orbax save — its multihost
        # coordination barriers require all participants; orbax's
        # primary-host logic ensures only the lead process actually writes
        # the replicated bytes.
        host_state = jax.tree_util.tree_map(
            lambda x: np.asarray(jax.device_get(x))
            if isinstance(x, (jax.Array, np.ndarray))
            else x,
            state,
        )
        _checkpointer().save(path, host_state, force=force)
        _write_layout_marker(path, "replicated")
    _process_barrier(f"ckpt_save:{path}")


def restore_checkpoint(
    path: str,
    like: Any,
    *,
    root_rank: int = 0,
    allow_layout_change: bool = False,
) -> Any:
    """Read the checkpoint at ``path`` and return it synchronized from
    ``root_rank`` and laid out like ``like`` (replicated over the mesh).

    The load-on-root-then-broadcast pattern (reference guidance,
    SURVEY.md §5 "Checkpoint/resume"): every process calls this; the root's
    bytes win and land replicated on every device. A sharded ``like``
    (FSDP/TP) instead restores collectively, each leaf landing directly in
    its training sharding — no host gather, no broadcast needed (the
    checkpoint bytes are the single source, so root_rank is moot).

    Elastic restore: a sharded checkpoint restores onto a DIFFERENT mesh
    topology whenever ``like`` carries the target shardings (orbax
    reshards on read) — e.g. resume a pod run on a smaller slice. Crossing
    the replicated↔sharded *layout family* (e.g. inspecting a pod FSDP
    checkpoint fully replicated on one host) is usually an accident, so
    the layout marker rejects it unless ``allow_layout_change=True``.
    """
    path = os.path.abspath(path)
    if _is_sharded_tree(like):
        if not allow_layout_change:
            _check_layout(path, "sharded")
        return _restore_sharded(path, like)
    if not allow_layout_change:
        _check_layout(path, "replicated")
    # The restore template only needs structure/shape/dtype — avoid pulling
    # the whole live state to host just to describe it.
    try:
        restored = _checkpointer().restore(path, item=_sds_template(like))
    except (TypeError, ValueError) as exc:
        if allow_layout_change:
            # The sharding-carrying template IS the safety mechanism of the
            # cross-family elastic restore; a bare host-array fallback would
            # let orbax consult the checkpoint's saved sharding file (save
            # topology's devices). Fail loudly instead of degrading.
            raise RuntimeError(
                "elastic cross-family restore needs an orbax version that "
                "accepts sharding-carrying ShapeDtypeStruct templates"
            ) from exc
        # Older orbax versions reject ShapeDtypeStruct templates; fall back to
        # a concrete-host-array template (same-topology restores only reach
        # here). Genuine restore errors (missing or corrupt checkpoint) raise
        # other exception types and propagate.
        restored = _checkpointer().restore(
            path,
            item=jax.tree_util.tree_map(
                lambda x: np.asarray(jax.device_get(x))
                if isinstance(x, (jax.Array, np.ndarray))
                else x,
                like,
            ),
        )
    synced = synchronize(restored, root_rank=root_rank)

    # Match leaf types/placement of `like` (replicated jax arrays), refusing
    # silent shape mismatches — restoring a (2,) kernel into a (3,) slot
    # must fail loudly, not produce a corrupted state.
    def _place(r, l):
        if isinstance(l, jax.Array):
            r_arr = jax.numpy.asarray(r, dtype=l.dtype)
            if r_arr.shape != l.shape:
                raise ValueError(
                    f"checkpoint leaf shape {r_arr.shape} does not match "
                    f"expected {l.shape}"
                )
            return jax.device_put(r_arr, l.sharding)
        return r

    return jax.tree_util.tree_map(_place, synced, like)


_STEP_DIR_RE = re.compile(r"^step_(\d{8})$")


class CheckpointManager:
    """Training-run checkpoint lifecycle on top of
    :func:`save_checkpoint`/:func:`restore_checkpoint` (VERDICT r2 next #7;
    the reference leaves all of this user-land, SURVEY.md §5
    "checkpoint/resume": ABSENT):

    - **step-numbered directories** ``<dir>/step_00000042`` — the layout
      marker the core writes *after* a save completes doubles as the commit
      marker, so a torn save is never listed as restorable;
    - **keep-k retention** (``max_to_keep``), oldest deleted after each
      successful save, lead process only;
    - **async save** (``async_save=True``): replicated state is snapshotted
      to host up front (donation-safe), then written on a single background
      thread (order preserved; each entry point waits for the previous
      save); sharded state always saves synchronously (collective);
      :meth:`wait_until_finished` joins;
    - **resume discovery**: :meth:`latest_step` / :meth:`restore` with
      ``step=None`` find the newest complete checkpoint.

    All methods must be called on every process (saves/restores of sharded
    state are collective).
    """

    def __init__(
        self,
        directory: str,
        *,
        max_to_keep: int | None = 3,
        async_save: bool = True,
    ):
        self.directory = os.path.abspath(directory)
        self.max_to_keep = max_to_keep
        os.makedirs(self.directory, exist_ok=True)
        self._executor = (
            ThreadPoolExecutor(max_workers=1, thread_name_prefix="ckpt")
            if async_save
            else None
        )
        self._pending: Future | None = None
        self._lock = threading.Lock()

    def _step_path(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step:08d}")

    def all_steps(self) -> list[int]:
        """Steps with *complete* checkpoints (layout marker present),
        ascending."""
        steps = []
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return []
        for name in names:
            m = _STEP_DIR_RE.match(name)
            if m and _read_layout_marker(
                os.path.join(self.directory, name)
            ) is not None:
                steps.append(int(m.group(1)))
        return sorted(steps)

    def latest_step(self) -> int | None:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def save(self, step: int, state: Any, *, force: bool = True) -> None:
        """Checkpoint ``state`` as ``step``; with ``async_save`` only the
        disk write runs in the background.

        Replicated state is snapshotted to host *synchronously* first:
        compiled train steps donate their input buffers by default, so the
        caller's next ``step(state, …)`` would tear the device buffers out
        from under a background ``device_get``. Sharded (FSDP/TP) state
        cannot be host-snapshotted without gathering, so its save runs
        synchronously (orbax still writes only per-process shards)."""
        if self._executor is None or _is_sharded_tree(state):
            self.wait_until_finished()
            self._save_and_retain(step, state, force)
            return
        snapshot = jax.tree_util.tree_map(
            lambda x: np.asarray(jax.device_get(x))
            if isinstance(x, (jax.Array, np.ndarray))
            else x,
            state,
        )
        # Submit under the lock so wait_until_finished always observes the
        # newest pending future; the single-worker executor runs saves in
        # submission order regardless. The wait on the *previous* save
        # happens OUTSIDE the lock: if a background save wedges (e.g. one
        # process never reaches a cross-process barrier), a lock-held wait
        # would deadlock wait_until_finished behind it too (ADVICE r3). The
        # post-submit wait still throttles to one queued snapshot and
        # surfaces the previous save's errors to this caller.
        with self._lock:
            prev = self._pending
            self._pending = self._executor.submit(
                self._save_and_retain, step, snapshot, force
            )
        if prev is not None:
            _wait_with_diagnostic(prev, "previous async checkpoint save")

    def _save_and_retain(self, step: int, state: Any, force: bool) -> None:
        save_checkpoint(self._step_path(step), state, force=force)
        if self.max_to_keep is not None:
            keep = set(self.all_steps()[-self.max_to_keep:])
            keep.add(step)
            if jax.process_index() == 0:
                for s in self.all_steps():
                    if s not in keep:
                        path = self._step_path(s)
                        # Marker first: once it is gone the step is
                        # invisible to discovery even if the rmtree below
                        # is interrupted.
                        try:
                            os.remove(_layout_marker_path(path))
                        except FileNotFoundError:
                            pass
                        shutil.rmtree(path, ignore_errors=True)

    def wait_until_finished(self) -> None:
        """Block until any in-flight async save has committed."""
        with self._lock:
            pending = self._pending
            self._pending = None
        if pending is not None:
            _wait_with_diagnostic(pending, "in-flight async checkpoint save")

    def restore(
        self,
        like: Any,
        *,
        step: int | None = None,
        allow_layout_change: bool = False,
    ) -> tuple[int, Any]:
        """Restore ``step`` (default: latest complete) as
        ``(step, state)``; raises ``FileNotFoundError`` when nothing is
        restorable. ``allow_layout_change`` forwards to
        :func:`restore_checkpoint` (elastic cross-family restore)."""
        self.wait_until_finished()
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no complete checkpoint under {self.directory}"
                )
        return step, restore_checkpoint(
            self._step_path(step), like,
            allow_layout_change=allow_layout_change,
        )

    def close(self) -> None:
        self.wait_until_finished()
        if self._executor is not None:
            self._executor.shutdown(wait=True)

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
