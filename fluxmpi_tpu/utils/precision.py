"""Mixed-precision policies and dynamic loss scaling.

Framework extra beyond the reference's scope (its precision story is
user-land Flux `f32`/`f16` conversion of the model; no policy object or
loss-scaler exists to mirror — the closest surface is the bf16-leaf
handling its allreduce staging preserves, src/comm.jl dtype passthrough,
which `fluxmpi_tpu.comm` already matches). Two pieces:

- :class:`Policy` — jmp-style (param, compute, output) dtype triple with
  pure-pytree cast helpers. On TPU the canonical policy is
  ``params=float32, compute=bfloat16, output=float32``: parameters and
  optimizer state stay f32 (update increments sit below bf16 resolution
  at realistic learning rates), matmuls/convs run bf16 on the MXU, and
  reductions/logits return to f32.

- :class:`DynamicLossScale` — the float16 survival kit: scale the loss
  up before the backward pass, unscale the gradients, halve the scale on
  inf/nan and grow it back after a run of finite steps. **bfloat16 does
  not need this** (same exponent range as f32); it exists for f16-style
  flows and API completeness, and is shaped as a pure state value that
  jits and donates cleanly inside a train step.

All casts touch only floating-point leaves — integer ids, bool masks,
and PRNG keys pass through untouched.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

__all__ = [
    "Policy",
    "get_policy",
    "DynamicLossScale",
    "loss_scale_init",
    "all_finite",
]


def _cast_floating(tree: Any, dtype) -> Any:
    if dtype is None:
        return tree

    def cast(x):
        arr = jnp.asarray(x)  # plain Python floats have no .astype
        return arr.astype(dtype) if jnp.issubdtype(
            arr.dtype, jnp.floating) else x

    return jax.tree_util.tree_map(cast, tree)


class Policy(NamedTuple):
    """(param, compute, output) dtype triple with pytree cast helpers.

    ``None`` for any slot means "leave as is". Use :func:`get_policy`
    for the string spelling (``"params=float32,compute=bfloat16,
    output=float32"`` or the ``"bf16"``/``"f32"`` shorthands).
    """

    param_dtype: Any = None
    compute_dtype: Any = None
    output_dtype: Any = None

    def cast_to_param(self, tree: Any) -> Any:
        """Float leaves → ``param_dtype`` (checkpoint / init layout)."""
        return _cast_floating(tree, self.param_dtype)

    def cast_to_compute(self, tree: Any) -> Any:
        """Float leaves → ``compute_dtype`` (entering the forward)."""
        return _cast_floating(tree, self.compute_dtype)

    def cast_to_output(self, tree: Any) -> Any:
        """Float leaves → ``output_dtype`` (leaving the forward)."""
        return _cast_floating(tree, self.output_dtype)


_SHORTHANDS = {
    # The canonical TPU training policy.
    "bf16": ("float32", "bfloat16", "float32"),
    "bfloat16": ("float32", "bfloat16", "float32"),
    # Full precision (the identity policy, spelled out).
    "f32": ("float32", "float32", "float32"),
    "float32": ("float32", "float32", "float32"),
    # f16 with f32 master params — pair with DynamicLossScale.
    "f16": ("float32", "float16", "float32"),
    "float16": ("float32", "float16", "float32"),
}


def get_policy(spec: str) -> Policy:
    """Parse ``"bf16"`` / ``"f32"`` / ``"f16"`` or the explicit
    ``"params=<dtype>,compute=<dtype>,output=<dtype>"`` form (any subset
    of the three keys; omitted slots mean "leave as is")."""
    spec = spec.strip().lower()
    if spec in _SHORTHANDS:
        p, c, o = _SHORTHANDS[spec]
        return Policy(jnp.dtype(p), jnp.dtype(c), jnp.dtype(o))
    slots = {"params": None, "compute": None, "output": None}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        key, sep, value = part.partition("=")
        key = key.strip()
        if not sep or key not in slots:
            raise ValueError(
                f"bad policy spec {spec!r}: expected 'params=<dtype>,"
                f"compute=<dtype>,output=<dtype>' (any subset) or one of "
                f"{sorted(set(_SHORTHANDS))}"
            )
        if slots[key] is not None:
            raise ValueError(f"bad policy spec {spec!r}: duplicate {key!r}")
        try:
            slots[key] = jnp.dtype(value.strip())
        except TypeError as e:
            raise ValueError(
                f"bad policy spec {spec!r}: {value.strip()!r} is not a "
                f"dtype (use full numpy/jax names, e.g. 'bfloat16', "
                f"'float16', 'float32')"
            ) from e
    if all(v is None for v in slots.values()):
        raise ValueError(f"bad policy spec {spec!r}: no slots given")
    return Policy(slots["params"], slots["compute"], slots["output"])


def all_finite(tree: Any) -> jax.Array:
    """Scalar bool: every float leaf is free of inf/nan (the gradient
    health check the loss scaler keys on)."""
    leaves = [
        jnp.isfinite(x).all()
        for x in jax.tree_util.tree_leaves(tree)
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
    ]
    if not leaves:
        return jnp.asarray(True)
    return jnp.stack(leaves).all()


class DynamicLossScale(NamedTuple):
    """Pure loss-scale state — arrays only, so it lives inside a jitted
    (and donated) train step without host round trips.

    Protocol per step::

        scaled_loss = ls.scale_loss(loss)       # before value_and_grad
        grads = ls.unscale(grads)               # after
        finite = all_finite(grads)
        ls = ls.adjust(finite)                  # halve on overflow, grow
        # apply the update only where `finite` (jnp.where on the trees)

    Growth doubles the scale after ``growth_interval`` consecutive
    finite steps (counter in the state); overflow halves it immediately
    and resets the counter. The scale is clamped to ``[1, 2**24]``.
    """

    scale: jax.Array  # f32 scalar
    counter: jax.Array  # i32 scalar: consecutive finite steps
    growth_interval: jax.Array  # i32 scalar (static-ish, rides the state)

    def scale_loss(self, loss: jax.Array) -> jax.Array:
        # Multiply in f32: an f16 loss would overflow at scale >= 2**16
        # (f16 max 65504), turning every scale-growth step into a fake
        # overflow. The f32 return is what the backward wants anyway.
        return loss.astype(jnp.float32) * self.scale

    def unscale(self, tree: Any) -> Any:
        inv = (1.0 / self.scale).astype(jnp.float32)

        def un(g):
            arr = jnp.asarray(g)  # plain Python floats have no .astype
            if not jnp.issubdtype(arr.dtype, jnp.floating):
                return g
            return (arr.astype(jnp.float32) * inv).astype(arr.dtype)

        return jax.tree_util.tree_map(un, tree)

    def adjust(self, grads_finite: jax.Array) -> "DynamicLossScale":
        counter = jnp.where(grads_finite, self.counter + 1, 0)
        grown = jnp.where(
            counter >= self.growth_interval, self.scale * 2.0, self.scale
        )
        counter = jnp.where(counter >= self.growth_interval, 0, counter)
        scale = jnp.where(grads_finite, grown, self.scale * 0.5)
        scale = jnp.clip(scale, 1.0, 2.0 ** 24)
        return DynamicLossScale(
            scale=scale.astype(jnp.float32),
            counter=counter.astype(jnp.int32),
            growth_interval=self.growth_interval,
        )


def loss_scale_init(
    initial: float = 2.0 ** 15, growth_interval: int = 2000
) -> DynamicLossScale:
    """Fresh :class:`DynamicLossScale` (defaults follow the common AMP
    recipe: start at 2^15, double after 2000 clean steps)."""
    if initial < 1:
        raise ValueError(f"initial scale must be >= 1, got {initial}")
    if growth_interval < 1:
        raise ValueError(
            f"growth_interval must be >= 1, got {growth_interval}"
        )
    return DynamicLossScale(
        scale=jnp.asarray(float(initial), jnp.float32),
        counter=jnp.asarray(0, jnp.int32),
        growth_interval=jnp.asarray(int(growth_interval), jnp.int32),
    )
