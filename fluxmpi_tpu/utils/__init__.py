"""Auxiliary subsystems: checkpointing, profiling, pytree helpers."""

from .checkpoint import restore_checkpoint, save_checkpoint  # noqa: F401
from .profiling import profile_trace, step_timer  # noqa: F401
