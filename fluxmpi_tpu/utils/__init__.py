"""Auxiliary subsystems: checkpointing, profiling, pytree helpers."""

from .checkpoint import (  # noqa: F401
    CheckpointManager,
    restore_checkpoint,
    save_checkpoint,
)
from .manifest import (  # noqa: F401
    MANIFEST_SCHEMA,
    build_manifest,
    manifest_path,
    read_manifest,
    validate_manifest,
    write_manifest,
)
from .flops import (  # noqa: F401
    chip_peak_flops,
    cost_analysis_flops,
    mfu,
)
from .profiling import (  # noqa: F401
    AutoProfiler,
    configure_auto_profiler,
    get_auto_profiler,
    maybe_auto_capture,
    profile_trace,
    set_auto_profiler,
    step_timer,
)
from .ema import EMAState, ema_init, ema_params, ema_update  # noqa: F401
from .precision import (  # noqa: F401
    DynamicLossScale,
    Policy,
    all_finite,
    get_policy,
    loss_scale_init,
)
