"""Auxiliary subsystems: checkpointing, profiling, pytree helpers."""

from .checkpoint import (  # noqa: F401
    CheckpointManager,
    restore_checkpoint,
    save_checkpoint,
)
from .profiling import profile_trace, step_timer  # noqa: F401
