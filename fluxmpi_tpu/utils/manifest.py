"""Checkpoint manifests: the topology sidecar of every save.

PR 5's checkpoints are bit-exact but mute about what they contain: a
restore needs a live ``like`` tree from the *saving* topology to know
what the bytes mean, so a run preempted on N hosts could only resume on
N hosts. The manifest fixes that: every :func:`~.checkpoint.save_checkpoint`
writes a schema-validated ``<path>.manifest.json`` next to the commit
marker recording

- the **global** shape/dtype and partition spec of every array leaf,
- the mesh axis names/sizes and controller process count at save time,
- the loader position *plus batch geometry* and the loop counters when
  the saved tree is a ``train_loop`` payload.

Restore then builds the resharding template internally: given the
manifest plus the *current* mesh (and optionally a partition rule from
:mod:`fluxmpi_tpu.parallel.sharding`), :func:`sharded_template` lays
every leaf out over the new topology and orbax reshards on read — N→M
for sharded (FSDP/TP) state, with
:class:`~fluxmpi_tpu.errors.TopologyMismatchError` naming any leaf the
new mesh cannot express. The schema (``fluxmpi_tpu.manifest/v1``) and
its stdlib-only validator live in :mod:`fluxmpi_tpu.telemetry.schema`
so ``scripts/check_metrics_schema.py`` validates manifests without
booting jax. See docs/fault_tolerance.md, "Elastic resume".
"""

from __future__ import annotations

import json
import os
import time
import warnings
from typing import Any

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..telemetry.schema import (
    MANIFEST_SCHEMA,
    _MANIFEST_LOADER_OPTIONAL,
    _MANIFEST_LOADER_REQUIRED,
    validate_manifest,
)

__all__ = [
    "MANIFEST_SCHEMA",
    "manifest_path",
    "build_manifest",
    "write_manifest",
    "read_manifest",
    "validate_manifest",
    "sharded_template",
    "check_manifest_shapes",
    "mesh_axes",
    "topology_changed",
]

_SUFFIX = ".manifest.json"


def manifest_path(path: str) -> str:
    """Sibling of the checkpoint directory (never inside it: orbax
    interprets directory contents as checkpoint tree entries), mirroring
    the layout-marker placement."""
    return path.rstrip(os.sep) + _SUFFIX


def _path_str(path: Any) -> str:
    """Key-path → stable string key, same spelling as
    :mod:`fluxmpi_tpu.parallel.sharding` rules use (``a/b/0/kernel``)."""
    parts = []
    for entry in path:
        if hasattr(entry, "key"):
            parts.append(str(entry.key))
        elif hasattr(entry, "name"):
            parts.append(str(entry.name))
        elif hasattr(entry, "idx"):
            parts.append(str(entry.idx))
        else:  # pragma: no cover - future jax key types
            parts.append(str(entry))
    return "/".join(parts)


def _encode_spec(spec: Any) -> list | None:
    """PartitionSpec → JSON (per-dim: null | axis | [axes]); None for
    "no layout opinion" (host arrays, unknown sharding kinds)."""
    if spec is None:
        return None
    out: list = []
    for names in tuple(spec):
        if names is None:
            out.append(None)
        elif isinstance(names, str):
            out.append(names)
        else:
            out.append([str(n) for n in names])
    return out


def decode_spec(encoded: list | None) -> P:
    """JSON spec entry → :class:`~jax.sharding.PartitionSpec`
    (``None`` decodes to fully replicated)."""
    if encoded is None:
        return P()
    dims: list = []
    for names in encoded:
        if names is None or isinstance(names, str):
            dims.append(names)
        else:
            dims.append(tuple(names))
    return P(*dims)


def _leaf_info(leaf: Any) -> tuple[tuple[int, ...], str, list | None] | None:
    """(global shape, dtype name, encoded spec) for an array-like leaf;
    None for opaque leaves (strings, callables, ...) which the manifest
    skips — restore keeps whatever the template carries for those.
    :class:`jax.ShapeDtypeStruct` counts as array-like: an abstract
    ``like`` tree is the natural spelling of "structure and global
    shapes only" on the elastic restore path."""
    if isinstance(leaf, (jax.Array, jax.ShapeDtypeStruct)):
        sharding = getattr(leaf, "sharding", None)
        spec = (
            _encode_spec(sharding.spec)
            if isinstance(sharding, NamedSharding)
            else None
        )
        return tuple(leaf.shape), np.dtype(leaf.dtype).name, spec
    try:
        arr = np.asarray(leaf)
    except Exception:
        return None
    if arr.dtype == object:
        return None
    return tuple(arr.shape), arr.dtype.name, None


def mesh_axes(mesh: Mesh | None) -> dict[str, int] | None:
    """Mesh → ordered ``{axis: size}`` (None passes through)."""
    if mesh is None:
        return None
    return {str(name): int(size) for name, size in mesh.shape.items()}


def _tree_mesh(tree: Any) -> Mesh | None:
    """The mesh named by the tree's own shardings, else the runtime's
    global mesh, else None (uninitialized host-only trees)."""
    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array) and isinstance(
            leaf.sharding, NamedSharding
        ):
            return leaf.sharding.mesh
    try:
        from ..runtime import global_mesh

        return global_mesh()
    except Exception:
        return None


def _scalar_int(x: Any) -> int | None:
    try:
        arr = np.asarray(x)
    except Exception:
        return None
    if arr.shape != () or not np.issubdtype(arr.dtype, np.integer):
        return None
    return int(arr)


def _int_section(tree: Any, section: str) -> dict[str, int] | None:
    """Hoist a ``train_loop`` payload section (``loader`` / ``loop``) of
    scalar-int leaves into plain manifest ints; None when the saved tree
    is not a payload (ad-hoc saves carry no position metadata)."""
    if not isinstance(tree, dict):
        return None
    sub = tree.get(section)
    if not isinstance(sub, dict) or not sub:
        return None
    out: dict[str, int] = {}
    for key, val in sub.items():
        as_int = _scalar_int(val)
        if as_int is None:
            return None
        out[str(key)] = as_int
    return out


def build_manifest(
    state: Any,
    *,
    layout: str,
    step: int | None = None,
    mesh: Mesh | None = None,
) -> dict[str, Any]:
    """Describe ``state`` (any pytree about to be checkpointed) as a
    ``fluxmpi_tpu.manifest/v1`` record. ``layout`` is the save layout
    (``"replicated"``/``"sharded"``, what the commit marker records);
    ``step`` the manager's step number when saved through one."""
    leaves = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        info = _leaf_info(leaf)
        if info is None:
            continue
        shape, dtype, spec = info
        leaves.append(
            {
                "path": _path_str(path),
                "shape": [int(d) for d in shape],
                "dtype": dtype,
                "spec": spec,
            }
        )
    # The ParallelConfig behind the mesh, when init(parallel=) built it:
    # restore tooling can then rebuild the SAME plan (axis sizes + names)
    # instead of reverse-engineering it from the mesh axes.
    parallel = None
    try:
        from ..runtime import global_plan

        plan = global_plan()
        if plan is not None:
            manifest_mesh_probe = mesh if mesh is not None else _tree_mesh(state)
            if manifest_mesh_probe is None or mesh_axes(
                plan.mesh
            ) == mesh_axes(manifest_mesh_probe):
                desc = plan.describe()
                parallel = {
                    "axes": desc["axes"],
                    "axis_names": desc["axis_names"],
                }
                fp = getattr(plan, "autotune_fingerprint", None)
                if fp:
                    # The layout autotuner picked this plan: record the
                    # bank key so a restore knows which banked record
                    # (the <ckpt>.autotune.json sidecar) vouches for
                    # the layout it is rebuilding.
                    parallel["autotune_fingerprint"] = str(fp)
    except Exception:
        parallel = None
    counters = _int_section(state, "loop")
    loop_keys = ("updates", "examples", "epochs")
    if counters is not None and sorted(counters) != sorted(loop_keys):
        counters = None
    loader = _int_section(state, "loader")
    if loader is not None and not (
        all(key in loader for key in _MANIFEST_LOADER_REQUIRED)
        and set(loader)
        <= set(_MANIFEST_LOADER_REQUIRED + _MANIFEST_LOADER_OPTIONAL)
    ):
        # An ad-hoc user tree with a loader-SHAPED int section is not a
        # train_loop payload; recording it would fail schema validation
        # and cost the whole sidecar (leaf specs included). Same guard
        # the counters section gets above.
        loader = None
    manifest_mesh = mesh if mesh is not None else _tree_mesh(state)
    return {
        "schema": MANIFEST_SCHEMA,
        "time_unix": time.time(),
        "step": int(step) if step is not None else None,
        "layout": layout,
        "process_count": jax.process_count(),
        "mesh": (
            {"axes": mesh_axes(manifest_mesh)}
            if manifest_mesh is not None
            else None
        ),
        "leaves": leaves,
        "loader": loader,
        "counters": counters,
        "parallel": parallel,
    }


def write_manifest(path: str, manifest: dict[str, Any]) -> None:
    """Write (fsync'd) the manifest beside the checkpoint at ``path``.
    Validates first: a save must never commit a manifest a later restore
    would reject."""
    errors = validate_manifest(manifest)
    if errors:
        raise ValueError(
            f"refusing to write an invalid checkpoint manifest for {path}: "
            + "; ".join(errors)
        )
    target = manifest_path(path)
    with open(target, "w", encoding="utf-8") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())


def read_manifest(path: str) -> dict[str, Any] | None:
    """Read and validate the manifest beside the checkpoint at ``path``.
    Returns None when absent (pre-elastic checkpoint — callers degrade
    to topology-blind behavior) or invalid (warned, never a crash: a
    corrupt sidecar must not brick a restorable checkpoint)."""
    target = manifest_path(path)
    try:
        with open(target, encoding="utf-8") as f:
            manifest = json.load(f)
    except FileNotFoundError:
        return None
    except (OSError, json.JSONDecodeError) as exc:
        warnings.warn(
            f"checkpoint manifest at {target} is unreadable ({exc!r}); "
            f"ignoring it — restore degrades to the topology-blind path",
            stacklevel=2,
        )
        return None
    errors = validate_manifest(manifest)
    if errors:
        warnings.warn(
            f"checkpoint manifest at {target} fails schema validation "
            f"({'; '.join(errors[:3])}); ignoring it — restore degrades to "
            f"the topology-blind path",
            stacklevel=2,
        )
        return None
    return manifest


def _leaves_by_path(manifest: dict[str, Any]) -> dict[str, dict[str, Any]]:
    return {leaf["path"]: leaf for leaf in manifest.get("leaves", [])}


def check_manifest_shapes(manifest: dict[str, Any], like: Any) -> None:
    """Refuse a restore whose template disagrees with the manifest about
    any leaf's *global* shape — the shape of a leaf is topology-invariant
    (specs are not), so a mismatch means wrong checkpoint family, and the
    error can name the leaf before any bytes move."""
    by_path = _leaves_by_path(manifest)
    for path, leaf in jax.tree_util.tree_flatten_with_path(like)[0]:
        info = _leaf_info(leaf)
        if info is None:
            continue
        entry = by_path.get(_path_str(path))
        if entry is None:
            continue
        shape = tuple(entry["shape"])
        if tuple(info[0]) != shape:
            raise ValueError(
                f"checkpoint leaf {_path_str(path)!r} shape {shape} (from "
                f"the manifest) does not match expected {tuple(info[0])} — "
                f"wrong checkpoint for this model/optimizer"
            )


def sharded_template(
    like: Any,
    manifest: dict[str, Any] | None,
    mesh: Mesh,
    rule: Any = None,
) -> Any:
    """Build the elastic restore template: ``like``'s structure with every
    array leaf replaced by a :class:`jax.ShapeDtypeStruct` carrying a
    :class:`~jax.sharding.NamedSharding` over the *current* ``mesh``.

    Layout source, per leaf: an explicit ``rule`` (a
    :data:`fluxmpi_tpu.parallel.sharding.Rule`) wins; otherwise the
    partition spec the manifest recorded at save time, re-validated
    against the new mesh — same axis names, new sizes. Validation is
    strict: an axis the new mesh lacks, or a dimension its size no
    longer divides, raises
    :class:`~fluxmpi_tpu.errors.TopologyMismatchError` naming the leaf
    (never a silent fall-back to replicated)."""
    from ..parallel.sharding import validated_spec_strict

    by_path = _leaves_by_path(manifest) if manifest is not None else {}

    def leaf_template(path: Any, leaf: Any) -> Any:
        info = _leaf_info(leaf)
        if info is None:
            return leaf
        shape, dtype, _ = info
        path_s = _path_str(path)
        entry = by_path.get(path_s)
        if rule is not None:
            spec = rule(path_s, shape)
        elif entry is not None:
            spec = decode_spec(entry.get("spec"))
        else:
            spec = P()
        spec = validated_spec_strict(spec, shape, mesh, path=path_s)
        return jax.ShapeDtypeStruct(
            shape, np.dtype(dtype), sharding=NamedSharding(mesh, spec)
        )

    return jax.tree_util.tree_map_with_path(leaf_template, like)


def topology_changed(
    manifest: dict[str, Any] | None, mesh: Mesh | None = None
) -> bool:
    """Did the world change since this manifest was written? True when
    the controller process count or the mesh axis sizes differ from the
    current ones (``mesh`` defaults to the runtime's global mesh); False
    when they match or the manifest predates topology recording."""
    if manifest is None:
        return False
    if int(manifest.get("process_count", 0)) != jax.process_count():
        return True
    saved_mesh = manifest.get("mesh")
    if saved_mesh is None:
        return False
    if mesh is None:
        try:
            from ..runtime import global_mesh

            mesh = global_mesh()
        except Exception:
            return False
    return dict(saved_mesh.get("axes") or {}) != mesh_axes(mesh)
