"""Shared FLOPs / MFU accounting for the bench harness and the live loop.

Promoted out of ``bench.py`` (which had the only MFU implementation in
the repo, usable solely offline) so the run-health plane
(:mod:`fluxmpi_tpu.telemetry.goodput`) computes **live** MFU with the
exact same peak table, cost-model fallback, and formula the bench
reports — one implementation, two consumers, no drift between the
offline number and the production one.

Deliberately import-light: nothing here imports jax at module scope
(``cost_analysis_flops`` only touches the compiled-step objects handed
to it), so ``bench.py``'s parent driver — which must never boot a
backend — can delegate to these helpers lazily from its children.
"""

from __future__ import annotations

from typing import Any

__all__ = [
    "chip_peak_flops",
    "cost_analysis_flops",
    "executable_cost",
    "executable_flops",
    "jaxpr_dot_flops",
    "mfu",
    "pallas_kernel_cost",
    "PEAK_FLOPS",
]

# Peak bf16 FLOPs/s per chip by device_kind substring (public spec
# sheets). Ordered: first substring match wins, so the more specific
# entries ("v5p") come before their prefixes would.
PEAK_FLOPS: tuple[tuple[str, float], ...] = (
    ("v6", 918e12),  # Trillium
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5 lite", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def chip_peak_flops(device_kind: str) -> float | None:
    """Peak bf16 FLOPs/s for a device kind, or None when unknown (CPU,
    future chips not yet in the table)."""
    kind = device_kind.lower()
    for sub, peak in PEAK_FLOPS:
        if sub in kind:
            return peak
    return None


def executable_flops(compiled: Any) -> float | None:
    """FLOPs per call of an ALREADY-compiled executable (the product of
    ``jit(...).lower().compile()``) from XLA's cost model, if exposed —
    the AOT twin of :func:`cost_analysis_flops`, used by the fused-window
    path which compiles its program once up front."""
    try:
        analysis = compiled.cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0] if analysis else None
        if analysis:
            flops = float(analysis.get("flops", 0.0))
            return flops if flops > 0 else None
    except Exception:
        pass
    return None


def executable_cost(compiled: Any) -> dict[str, float] | None:
    """FLOPs AND bytes-accessed per call of an already-compiled
    executable — :func:`executable_flops` grown with the memory-traffic
    term the layout autotuner's static score needs (an all-gather the
    partitioner inserted shows up as bytes accessed, not FLOPs).
    Returns ``{"flops": ..., "bytes_accessed": ...}`` with absent /
    non-positive entries as 0.0, or None when the backend exposes no
    cost model at all."""
    try:
        analysis = compiled.cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0] if analysis else None
        if analysis:
            flops = float(analysis.get("flops", 0.0) or 0.0)
            accessed = float(analysis.get("bytes accessed", 0.0) or 0.0)
            if not accessed:
                # Some backends report only the split per-operand form
                # ("bytes accessed operand N{}", "bytes accessed output").
                accessed = sum(
                    float(v or 0.0)
                    for k, v in analysis.items()
                    if isinstance(k, str) and k.startswith("bytes accessed")
                )
            return {
                "flops": max(flops, 0.0),
                "bytes_accessed": max(accessed, 0.0),
            }
    except Exception:
        pass
    return None


def _iter_subjaxprs(jaxpr: Any):
    """Nested jaxprs reachable from one jaxpr's equation params (cond
    branches, scan/while bodies, pjit/custom_vjp call bodies) — duck-typed
    so this module still never imports jax."""
    for eqn in jaxpr.eqns:
        for v in eqn.params.values():
            vs = v if isinstance(v, (list, tuple)) else (v,)
            for w in vs:
                if hasattr(w, "eqns"):
                    yield eqn, w
                elif hasattr(w, "jaxpr") and hasattr(w.jaxpr, "eqns"):
                    yield eqn, w.jaxpr


def _prod(shape) -> float:
    out = 1.0
    for s in shape:
        out *= float(s)
    return out


def jaxpr_dot_flops(jaxpr: Any) -> float:
    """Total ``dot_general`` FLOPs in a jaxpr, recursing into nested
    jaxprs (2 × output elements × contraction length per dot). ``scan``
    bodies multiply by the trip count; ``cond`` counts every branch and
    ``while`` bodies count once — for kernels that guard compute behind
    a predicate (the flash kernels' masked-tile skip) the result is an
    upper bound on the executed matmul work, which is the right sign for
    a cost model."""
    total = 0.0
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "dot_general":
            (lhs_c, _), _ = eqn.params["dimension_numbers"]
            lhs = eqn.invars[0].aval
            contract = _prod(lhs.shape[d] for d in lhs_c)
            total += 2.0 * _prod(eqn.outvars[0].aval.shape) * contract
    for eqn, sub in _iter_subjaxprs(jaxpr):
        inner = jaxpr_dot_flops(sub)
        if eqn.primitive.name == "scan":
            inner *= float(eqn.params.get("length", 1))
        total += inner
    return total


def pallas_kernel_cost(jaxpr: Any) -> dict[str, float] | None:
    """Analytic cost of every ``pallas_call`` in a (closed) jaxpr —
    the kernel-plane term XLA's cost model cannot see (a pallas kernel
    lowers to an opaque custom call, so its matmuls and HBM traffic
    report as zero; a layout autotuner scoring on XLA cost alone would
    think flash attention is free).

    FLOPs: per-grid-point ``dot_general`` work of the kernel body
    (block-shaped avals) × the grid size. Bytes: the streamed sizes of
    the call's global operands and results — the flash-style ideal where
    each operand crosses HBM O(1) times, which is exactly the advantage
    the score should see over a dense attend's materialized [s, s]
    scores. Returns ``{"flops", "bytes_accessed", "calls"}`` or None
    when the jaxpr holds no pallas calls. Tile-skip predicates (causal /
    fully-masked tiles) are not modeled — the FLOPs term is an upper
    bound."""
    closed = getattr(jaxpr, "jaxpr", None)
    root = closed if closed is not None and hasattr(closed, "eqns") else jaxpr
    calls: list[Any] = []

    def find(jx):
        for eqn in jx.eqns:
            if eqn.primitive.name == "pallas_call":
                calls.append(eqn)
        for _, sub in _iter_subjaxprs(jx):
            find(sub)

    find(root)
    if not calls:
        return None
    flops = 0.0
    bytes_accessed = 0.0
    for eqn in calls:
        body = eqn.params.get("jaxpr")
        grid = getattr(eqn.params.get("grid_mapping"), "grid", ()) or ()
        if body is not None:
            flops += _prod(grid) * jaxpr_dot_flops(body)
        for v in (*eqn.invars, *eqn.outvars):
            aval = getattr(v, "aval", None)
            if aval is not None and hasattr(aval, "shape"):
                bytes_accessed += _prod(aval.shape) * float(
                    getattr(aval.dtype, "itemsize", 4)
                )
    return {
        "flops": flops,
        "bytes_accessed": bytes_accessed,
        "calls": float(len(calls)),
    }


def cost_analysis_flops(step: Any, state: Any, data: Any) -> float | None:
    """FLOPs per compiled step call straight from XLA's cost model, if
    exposed. ``step`` is anything with a ``.lower(state, data)`` (a
    ``jax.jit`` wrapper or a :func:`~fluxmpi_tpu.parallel.make_train_step`
    product); lowering does not execute or consume donated buffers, so
    it is safe to call on the live pre-first-dispatch state."""
    try:
        return executable_flops(step.lower(state, data).compile())
    except Exception:
        return None


def mfu(
    flops_per_step: float | None,
    rate: float,
    n_dev: int,
    device_kind: str | None = None,
    *,
    peak: float | None = None,
) -> float | None:
    """Model FLOPs utilization per chip: FLOPs/step × steps/sec ÷
    (chips × peak), rounded to 4 places.

    Returns None when the FLOPs estimate or the peak is unknown
    (``peak`` overrides the ``device_kind`` table lookup — the live
    tracker's hook for tests and unlisted chips). The RAW value is
    returned even when it exceeds 1.0 — an impossible number means a
    broken clock or FLOPs estimate, and the *caller* decides whether to
    discard it (``bench.py`` does, recording ``mfu_discarded``) or to
    surface it."""
    if not flops_per_step:
        return None
    if peak is None:
        if device_kind is None:
            return None
        peak = chip_peak_flops(device_kind)
    if peak is None or peak <= 0 or n_dev < 1:
        return None
    return round(flops_per_step * rate / (n_dev * peak), 4)
