"""Shared FLOPs / MFU accounting for the bench harness and the live loop.

Promoted out of ``bench.py`` (which had the only MFU implementation in
the repo, usable solely offline) so the run-health plane
(:mod:`fluxmpi_tpu.telemetry.goodput`) computes **live** MFU with the
exact same peak table, cost-model fallback, and formula the bench
reports — one implementation, two consumers, no drift between the
offline number and the production one.

Deliberately import-light: nothing here imports jax at module scope
(``cost_analysis_flops`` only touches the compiled-step objects handed
to it), so ``bench.py``'s parent driver — which must never boot a
backend — can delegate to these helpers lazily from its children.
"""

from __future__ import annotations

from typing import Any

__all__ = [
    "chip_peak_flops",
    "cost_analysis_flops",
    "executable_cost",
    "executable_flops",
    "mfu",
    "PEAK_FLOPS",
]

# Peak bf16 FLOPs/s per chip by device_kind substring (public spec
# sheets). Ordered: first substring match wins, so the more specific
# entries ("v5p") come before their prefixes would.
PEAK_FLOPS: tuple[tuple[str, float], ...] = (
    ("v6", 918e12),  # Trillium
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5 lite", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def chip_peak_flops(device_kind: str) -> float | None:
    """Peak bf16 FLOPs/s for a device kind, or None when unknown (CPU,
    future chips not yet in the table)."""
    kind = device_kind.lower()
    for sub, peak in PEAK_FLOPS:
        if sub in kind:
            return peak
    return None


def executable_flops(compiled: Any) -> float | None:
    """FLOPs per call of an ALREADY-compiled executable (the product of
    ``jit(...).lower().compile()``) from XLA's cost model, if exposed —
    the AOT twin of :func:`cost_analysis_flops`, used by the fused-window
    path which compiles its program once up front."""
    try:
        analysis = compiled.cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0] if analysis else None
        if analysis:
            flops = float(analysis.get("flops", 0.0))
            return flops if flops > 0 else None
    except Exception:
        pass
    return None


def executable_cost(compiled: Any) -> dict[str, float] | None:
    """FLOPs AND bytes-accessed per call of an already-compiled
    executable — :func:`executable_flops` grown with the memory-traffic
    term the layout autotuner's static score needs (an all-gather the
    partitioner inserted shows up as bytes accessed, not FLOPs).
    Returns ``{"flops": ..., "bytes_accessed": ...}`` with absent /
    non-positive entries as 0.0, or None when the backend exposes no
    cost model at all."""
    try:
        analysis = compiled.cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0] if analysis else None
        if analysis:
            flops = float(analysis.get("flops", 0.0) or 0.0)
            accessed = float(analysis.get("bytes accessed", 0.0) or 0.0)
            if not accessed:
                # Some backends report only the split per-operand form
                # ("bytes accessed operand N{}", "bytes accessed output").
                accessed = sum(
                    float(v or 0.0)
                    for k, v in analysis.items()
                    if isinstance(k, str) and k.startswith("bytes accessed")
                )
            return {
                "flops": max(flops, 0.0),
                "bytes_accessed": max(accessed, 0.0),
            }
    except Exception:
        pass
    return None


def cost_analysis_flops(step: Any, state: Any, data: Any) -> float | None:
    """FLOPs per compiled step call straight from XLA's cost model, if
    exposed. ``step`` is anything with a ``.lower(state, data)`` (a
    ``jax.jit`` wrapper or a :func:`~fluxmpi_tpu.parallel.make_train_step`
    product); lowering does not execute or consume donated buffers, so
    it is safe to call on the live pre-first-dispatch state."""
    try:
        return executable_flops(step.lower(state, data).compile())
    except Exception:
        return None


def mfu(
    flops_per_step: float | None,
    rate: float,
    n_dev: int,
    device_kind: str | None = None,
    *,
    peak: float | None = None,
) -> float | None:
    """Model FLOPs utilization per chip: FLOPs/step × steps/sec ÷
    (chips × peak), rounded to 4 places.

    Returns None when the FLOPs estimate or the peak is unknown
    (``peak`` overrides the ``device_kind`` table lookup — the live
    tracker's hook for tests and unlisted chips). The RAW value is
    returned even when it exceeds 1.0 — an impossible number means a
    broken clock or FLOPs estimate, and the *caller* decides whether to
    discard it (``bench.py`` does, recording ``mfu_discarded``) or to
    surface it."""
    if not flops_per_step:
        return None
    if peak is None:
        if device_kind is None:
            return None
        peak = chip_peak_flops(device_kind)
    if peak is None or peak <= 0 or n_dev < 1:
        return None
    return round(flops_per_step * rate / (n_dev * peak), 4)
