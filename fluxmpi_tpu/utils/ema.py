"""Exponential moving average of a parameter pytree.

Framework extra beyond the reference's scope (its optimizer layer is
Optimisers.jl user-land; no EMA utility exists to mirror): diffusion
models sample from EMA weights as a matter of course, and large-batch
vision training uses them for eval. TPU-first shape: both functions are
pure pytree maps that jit/donate cleanly — for peak throughput fold
``ema_update`` into the compiled train step (one fused program, no extra
dispatch); an eager per-step call is fine when the step itself is the
bottleneck (toys, eval loops).

The running mean accumulates in float32 regardless of the param dtype:
with bf16 params and decay 0.999 the per-step increment sits below
bf16's relative resolution (and the decay constant itself quantizes), so
a bf16 accumulator silently stops updating. ``ema_params`` returns the
f32 average; flax modules cast per their own ``dtype`` at apply time.

Debiasing follows Adam's ``1 - decay**t`` correction so early averages
track the live params instead of the zero init; the decay is recorded in
the state at ``ema_init`` time, so update and readout can never disagree
about it.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

__all__ = ["EMAState", "ema_init", "ema_update", "ema_params"]


def _acc_dtype(dtype) -> jnp.dtype:
    return jnp.promote_types(dtype, jnp.float32)


class EMAState(NamedTuple):
    """Running average + bookkeeping (a pytree; checkpoints like any
    other state)."""

    mean: Any
    count: jnp.ndarray  # int32 scalar
    decay: jnp.ndarray  # f32 scalar, fixed at ema_init


def ema_init(params, decay: float = 0.999) -> EMAState:
    """Start an EMA at zero with count 0 (debiasing makes the zero init
    exact: after one update ``ema_params`` returns the params
    themselves)."""
    if not 0.0 < decay < 1.0:
        raise ValueError(f"decay must be in (0, 1), got {decay}")
    zeros = jax.tree_util.tree_map(
        lambda p: jnp.zeros(jnp.shape(p), _acc_dtype(jnp.asarray(p).dtype)),
        params,
    )
    return EMAState(mean=zeros, count=jnp.zeros((), jnp.int32),
                    decay=jnp.float32(decay))


def ema_update(state: EMAState, params) -> EMAState:
    """One EMA step: ``mean <- decay * mean + (1 - decay) * params``
    (f32 accumulation; the decay comes from the state)."""
    d = state.decay
    mean = jax.tree_util.tree_map(
        lambda m, p: d * m + (1.0 - d) * p.astype(m.dtype),
        state.mean, params,
    )
    return EMAState(mean=mean, count=state.count + 1, decay=d)


def ema_params(state: EMAState):
    """The debiased average: ``mean / (1 - decay**count)``, in f32.

    Raises if no update has been applied (the correction would divide by
    zero and the zero init carries no information). Under jit the count
    is a tracer and the guard is skipped — the caller owns the
    at-least-one-update invariant there.
    """
    if not isinstance(state.count, jax.core.Tracer) and int(state.count) == 0:
        raise ValueError("ema_params before any ema_update")
    corr = 1.0 - state.decay ** state.count.astype(jnp.float32)
    return jax.tree_util.tree_map(lambda m: m / corr.astype(m.dtype),
                                  state.mean)
