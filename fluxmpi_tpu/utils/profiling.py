"""Profiling / tracing helpers.

The reference has no tracing subsystem (SURVEY.md §5: only wall-clock
deltas in example scripts). On TPU the JAX profiler is nearly free to
expose: :func:`profile_trace` captures an XPlane trace viewable in
TensorBoard/Perfetto; :func:`step_timer` gives honest step timings around
async dispatch (blocks on results — the ``MPI.Waitall!`` of timing).

:class:`AutoProfiler` turns the XPlane capture into a *triggered*
instrument: armed via ``FLUXMPI_TPU_PROFILE_DIR`` (or
``init(profile=...)``), it captures one bounded-duration profiler window
when the anomaly detector fires a ``step_time_regression`` or
``steady_state_retrace`` (see :mod:`fluxmpi_tpu.telemetry.anomaly`) or
on ``SIGUSR2`` — so the evidence for a live perf regression is on disk
before a human opens a terminal. Captures are rate-limited (default:
once per run) because a regressing run would otherwise re-trigger at
every flush and profile itself to death.
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading
import time
import warnings
from typing import Any, Iterator

import jax

__all__ = [
    "profile_trace",
    "step_timer",
    "AutoProfiler",
    "get_auto_profiler",
    "set_auto_profiler",
    "maybe_auto_capture",
    "configure_auto_profiler",
    "shutdown_auto_profiler",
]

_ENV_PROFILE_DIR = "FLUXMPI_TPU_PROFILE_DIR"
_ENV_PROFILE_SECONDS = "FLUXMPI_TPU_PROFILE_SECONDS"
_ENV_PROFILE_LIMIT = "FLUXMPI_TPU_PROFILE_LIMIT"


def _per_process_dir(logdir: str) -> str:
    """Each process's private capture directory under a shared logdir:
    ``<logdir>/proc<k>`` in a multi-process world (the XPlane writers
    otherwise collide on the shared path), the plain logdir when
    single-process (no surprise nesting)."""
    if jax.process_count() > 1:  # pragma: no cover - multihost only
        return os.path.join(logdir, f"proc{jax.process_index()}")
    return logdir


@contextlib.contextmanager
def profile_trace(
    logdir: str, *, all_hosts: bool = False, host_only: bool | None = None
) -> Iterator[None]:
    """Capture a profiler trace of the enclosed block into ``logdir``.

    By default only the lead process traces — device activity is
    mirrored across DP replicas, so one host's XPlane is usually the
    whole picture. Pass ``all_hosts=True`` to trace on every process
    (straggler hunts, where the point is comparing hosts); each process
    then writes into its own ``<logdir>/proc<k>`` subdirectory
    automatically, so one shared logdir (GCS bucket, NFS path) works —
    the writers no longer collide.

    ``host_only`` is the deprecated spelling of this switch: it was
    documented as "only the lead process traces" but implemented so
    ``host_only=True`` made *every* process trace. The shim preserves
    each caller's old *actual* behavior (``all_hosts = host_only``) —
    ``host_only=False`` callers keep their correct lead-only traces,
    ``host_only=True`` callers keep tracing everywhere — while the
    deprecation warning points at the honest spelling.

    View with TensorBoard's profile plugin or Perfetto. For the
    always-on, in-process span timeline (no XPlane machinery), see
    :mod:`fluxmpi_tpu.telemetry.tracing`.
    """
    if host_only is not None:
        warnings.warn(
            "profile_trace(host_only=...) is deprecated: the flag's old "
            "behavior contradicted its documentation (host_only=True "
            "traced on EVERY process). Behavior is preserved; spell it "
            "all_hosts=True to trace on every process, or omit the flag "
            "to trace on the lead process only.",
            DeprecationWarning,
            stacklevel=3,
        )
        all_hosts = bool(host_only)
    if all_hosts:
        with jax.profiler.trace(_per_process_dir(logdir)):
            yield
    elif jax.process_index() == 0:
        with jax.profiler.trace(logdir):
            yield
    else:  # pragma: no cover - multihost only
        yield


class AutoProfiler:
    """Anomaly/signal-triggered XPlane capture with a per-run budget.

    Args:
      logdir: capture destination; every process writes into its own
        ``<logdir>/proc<k>`` subdirectory in a multi-process world (the
        :func:`profile_trace` collision contract). Each capture lands in
        the profiler's own timestamped subtree, so repeated captures
        coexist.
      seconds: bounded capture window. The capture runs on a daemon
        thread — ``start_trace`` now, ``stop_trace`` after the window —
        so the training loop keeps running *inside* the captured window
        (that running work IS the evidence).
      limit: automatic captures allowed per run (default 1 — a
        regressing run re-triggers at every flush; the first capture is
        the evidence, the rest would be overhead). ``SIGUSR2`` /
        ``force=True`` captures bypass the budget (a human asked), but
        never overlap a live capture.
    """

    def __init__(
        self,
        logdir: str,
        *,
        seconds: float = 3.0,
        limit: int = 1,
    ):
        if seconds <= 0:
            raise ValueError(f"seconds must be > 0, got {seconds}")
        if limit < 0:
            raise ValueError(f"limit must be >= 0, got {limit}")
        self.logdir = logdir
        self.seconds = float(seconds)
        self.limit = int(limit)
        self._lock = threading.Lock()
        self._captures = 0
        self._auto_captures = 0
        self._capturing = False
        self._thread: threading.Thread | None = None
        self._prev_sigusr2: Any = None
        self.last_capture_path: str | None = None
        self.last_reason: str | None = None

    @property
    def captures(self) -> int:
        """Captures started so far (auto + forced)."""
        return self._captures

    def reset(self) -> None:
        """Restore the automatic-capture budget (``train_loop`` calls
        this per run). Only the budget re-opens — :attr:`captures`
        stays a monotonic total of every window started."""
        with self._lock:
            self._auto_captures = 0

    def maybe_capture(self, reason: str, *, force: bool = False) -> str | None:
        """Start one bounded capture if the budget allows (``force``
        bypasses the budget, not the no-overlap rule). Returns the
        capture directory, or None when skipped. Non-blocking: the
        window closes on a daemon thread; :meth:`wait` joins it."""
        with self._lock:
            if self._capturing:
                return None
            if not force:
                # Only automatic triggers spend the budget — an early
                # SIGUSR2 must not eat the one capture a later anomaly
                # exists to write.
                if self._auto_captures >= self.limit:
                    return None
                self._auto_captures += 1
            self._captures += 1
            self._capturing = True
        logdir = _per_process_dir(self.logdir)
        thread = threading.Thread(
            target=self._capture,
            args=(logdir, not force),
            name="fluxmpi-autoprofile",
            daemon=True,
        )
        self.last_capture_path = logdir
        self.last_reason = reason
        self._thread = thread
        thread.start()
        return logdir

    def _capture(self, logdir: str, auto: bool) -> None:
        started = False
        try:
            jax.profiler.start_trace(logdir)
            started = True
            # Announce only an OPEN window — a premature success line
            # would send an operator to an empty directory when the
            # session failed to start.
            print(
                f"fluxmpi_tpu auto-profiler: capturing {self.seconds:g}s "
                f"XPlane window into {logdir} "
                f"(reason: {self.last_reason})",
                file=sys.stderr,
            )
            time.sleep(self.seconds)
        except Exception:  # the profiler must never kill the run
            pass
        finally:
            # Stop ONLY a session this thread started: if start_trace
            # failed because another profiler session is live (a user's
            # profile_trace), an unconditional stop would terminate
            # THEIR capture mid-window and crash their context exit.
            if started:
                try:
                    jax.profiler.stop_trace()
                except Exception:
                    pass
            with self._lock:
                self._capturing = False
                if not started:
                    # Refund: a capture that never opened wrote nothing
                    # — the budget must stay available for the next
                    # trigger instead of ending the run evidence-less.
                    # Clamped: a reset() racing the stalled start must
                    # not underflow the budget into limit+1 captures.
                    self._captures = max(0, self._captures - 1)
                    if auto:
                        self._auto_captures = max(
                            0, self._auto_captures - 1
                        )
            if not started:
                print(
                    f"fluxmpi_tpu auto-profiler: capture into {logdir} "
                    f"failed to start (another profiler session live?); "
                    f"budget refunded",
                    file=sys.stderr,
                )

    def wait(self, timeout: float | None = None) -> None:
        """Join the in-flight capture window, if any (tests; shutdown)."""
        thread = self._thread
        if thread is not None:
            thread.join(timeout)

    # -- SIGUSR2 dump-on-demand (the watchdog's SIGUSR1 discipline) ----

    def _on_sigusr2(self, signum: int, frame: Any) -> None:
        # Signal handlers run between bytecodes on the main thread;
        # start_trace takes profiler-internal locks, so the handler only
        # spawns the capture thread (thread creation takes no user
        # locks) and returns.
        threading.Thread(
            target=self.maybe_capture,
            args=("signal",),
            kwargs={"force": True},
            daemon=True,
        ).start()

    def install_signal(self) -> None:
        """Install the SIGUSR2 capture-on-demand handler (main thread
        only; degrades silently elsewhere — the triggered path still
        works, only dump-on-demand is lost)."""
        import signal

        try:
            self._prev_sigusr2 = signal.signal(
                signal.SIGUSR2, self._on_sigusr2
            )
        except (ValueError, OSError, AttributeError):
            self._prev_sigusr2 = None

    def uninstall_signal(self) -> None:
        import signal

        if self._prev_sigusr2 is not None:
            try:
                signal.signal(signal.SIGUSR2, self._prev_sigusr2)
            except (ValueError, OSError):
                pass
            self._prev_sigusr2 = None


_auto: AutoProfiler | None = None


def get_auto_profiler() -> AutoProfiler | None:
    """The armed auto-profiler, if any (None = triggered capture off)."""
    return _auto


def set_auto_profiler(profiler: AutoProfiler | None) -> AutoProfiler | None:
    """Install (or, with None, remove) the process auto-profiler;
    returns the previous one. Signal handlers are the caller's business
    (``configure_auto_profiler`` installs them)."""
    global _auto
    prev, _auto = _auto, profiler
    return prev


def maybe_auto_capture(reason: str) -> str | None:
    """Trigger the armed auto-profiler (no-op returning None when none
    is armed) — what the anomaly detector calls on
    ``step_time_regression`` / ``steady_state_retrace``."""
    ap = _auto
    if ap is None:
        return None
    return ap.maybe_capture(reason)


def configure_auto_profiler(spec: Any = None) -> AutoProfiler | None:
    """Wire triggered profiling from a one-value spec (mirror of
    :func:`fluxmpi_tpu.telemetry.configure`):

    - ``None`` — read ``FLUXMPI_TPU_PROFILE_DIR`` (no-op when
      unset/empty); window seconds and the per-run capture limit come
      from ``FLUXMPI_TPU_PROFILE_SECONDS`` (default 3) and
      ``FLUXMPI_TPU_PROFILE_LIMIT`` (default 1);
    - ``False`` / ``"0"`` — disarm (restores SIGUSR2);
    - a path string — arm an :class:`AutoProfiler` at that logdir;
    - an :class:`AutoProfiler` — arm it.

    Arming installs the ``SIGUSR2`` capture-on-demand handler. Called by
    ``fluxmpi_tpu.init(profile=...)``; idempotent — a replay with the
    same logdir/window keeps the armed instance AND its spent capture
    budget (``init()`` replays must not grant a fresh budget)."""
    global _auto
    if spec is None:
        spec = os.environ.get(_ENV_PROFILE_DIR)
        if spec is None or spec == "":
            return _auto
    if spec is False or spec == "0":
        shutdown_auto_profiler()
        return None
    if isinstance(spec, AutoProfiler):
        if spec is _auto:
            return spec
        shutdown_auto_profiler()
        set_auto_profiler(spec)
        spec.install_signal()
        return spec
    if not isinstance(spec, str):
        raise ValueError(
            f"profile spec must be a logdir path, False/'0', or an "
            f"AutoProfiler; got {spec!r}"
        )
    seconds = float(os.environ.get(_ENV_PROFILE_SECONDS) or 3.0)
    limit = int(os.environ.get(_ENV_PROFILE_LIMIT) or 1)
    if (
        _auto is not None
        and _auto.logdir == spec
        and _auto.seconds == seconds
        and _auto.limit == limit
    ):
        return _auto  # idempotent init() replay
    shutdown_auto_profiler()
    ap = AutoProfiler(spec, seconds=seconds, limit=limit)
    set_auto_profiler(ap)
    ap.install_signal()
    return ap


def shutdown_auto_profiler() -> None:
    """Disarm the auto-profiler: wait out any live capture window,
    restore SIGUSR2, and forget the instance (capture budgets must not
    leak across init cycles — the fault-plane leak rule)."""
    global _auto
    ap = _auto
    if ap is None:
        return
    # start_trace itself can stall for seconds on a cold profiler
    # backend; give the window generous room before abandoning it.
    ap.wait(timeout=ap.seconds + 60.0)
    ap.uninstall_signal()
    _auto = None


# One cached jitted sentinel for step_timer's no-watch fallback. A fresh
# `jax.jit(lambda x: x + 1)` per call would be a NEW jit cache entry each
# time (lambda identity keys the cache), so every timed step would
# retrace — the drain itself would dirty the timing it exists to honor.
_sentinel_bump = None


def _bump_fn():
    global _sentinel_bump
    if _sentinel_bump is None:
        _sentinel_bump = jax.jit(lambda x: x + 1)
    return _sentinel_bump


class _TimerHandle:
    def __init__(self) -> None:
        self._watched: list[Any] = []

    def watch(self, tree: Any) -> Any:
        """Register outputs to block on before the clock stops (returns the
        tree for inline use: ``out = t.watch(step(...))``)."""
        self._watched.append(tree)
        return tree


@contextlib.contextmanager
def step_timer(
    result_holder: dict,
    key: str = "seconds",
    *,
    metric: str | None = None,
    registry: Any | None = None,
) -> Iterator[_TimerHandle]:
    """Time the enclosed block including async-dispatched device work.

    Register the block's outputs with ``handle.watch(out)`` so the timer
    blocks on them before stopping the clock (the ``MPI.Waitall!`` of
    timing). With nothing watched, a sentinel computation is enqueued per
    local device and blocked on — TPU executes programs in order per
    device, so this drains prior dispatched work.

    ``metric="train.step_seconds"`` additionally observes the elapsed
    time into a telemetry histogram of that name (on ``registry``, or the
    default :func:`fluxmpi_tpu.telemetry.get_registry` when omitted) —
    the bridge between this timing discipline and the metrics substrate.
    """
    handle = _TimerHandle()
    t0 = time.perf_counter()
    yield handle
    if handle._watched:
        jax.block_until_ready(handle._watched)
    else:
        import jax.numpy as jnp

        bump = _bump_fn()
        for d in jax.local_devices():
            bump(jax.device_put(jnp.zeros(()), d)).block_until_ready()
    elapsed = time.perf_counter() - t0
    result_holder[key] = elapsed
    if metric is not None:
        if registry is None:
            from ..telemetry import get_registry

            registry = get_registry()
        registry.histogram(metric).observe(elapsed)


def block_on(tree: Any) -> Any:
    """Block until every array in ``tree`` is ready (the timing analogue of
    ``MPI.Waitall!``, reference src/optimizer.jl:59). Returns the tree."""
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "block_until_ready"):
            leaf.block_until_ready()
    return tree
