"""Profiling / tracing helpers.

The reference has no tracing subsystem (SURVEY.md §5: only wall-clock
deltas in example scripts). On TPU the JAX profiler is nearly free to
expose: :func:`profile_trace` captures an XPlane trace viewable in
TensorBoard/Perfetto; :func:`step_timer` gives honest step timings around
async dispatch (blocks on results — the ``MPI.Waitall!`` of timing).
"""

from __future__ import annotations

import contextlib
import time
import warnings
from typing import Any, Iterator

import jax

__all__ = ["profile_trace", "step_timer"]


@contextlib.contextmanager
def profile_trace(
    logdir: str, *, all_hosts: bool = False, host_only: bool | None = None
) -> Iterator[None]:
    """Capture a profiler trace of the enclosed block into ``logdir``.

    By default only the lead process traces — device activity is
    mirrored across DP replicas, so one host's XPlane is usually the
    whole picture. Pass ``all_hosts=True`` to trace on every process
    (straggler hunts, where the point is comparing hosts); give each
    host its own ``logdir`` then, or the writers collide.

    ``host_only`` is the deprecated spelling of this switch: it was
    documented as "only the lead process traces" but implemented so
    ``host_only=True`` made *every* process trace. The shim preserves
    each caller's old *actual* behavior (``all_hosts = host_only``) —
    ``host_only=False`` callers keep their correct lead-only traces,
    ``host_only=True`` callers keep tracing everywhere — while the
    deprecation warning points at the honest spelling.

    View with TensorBoard's profile plugin or Perfetto. For the
    always-on, in-process span timeline (no XPlane machinery), see
    :mod:`fluxmpi_tpu.telemetry.tracing`.
    """
    if host_only is not None:
        warnings.warn(
            "profile_trace(host_only=...) is deprecated: the flag's old "
            "behavior contradicted its documentation (host_only=True "
            "traced on EVERY process). Behavior is preserved; spell it "
            "all_hosts=True to trace on every process, or omit the flag "
            "to trace on the lead process only.",
            DeprecationWarning,
            stacklevel=3,
        )
        all_hosts = bool(host_only)
    if all_hosts or jax.process_index() == 0:
        with jax.profiler.trace(logdir):
            yield
    else:  # pragma: no cover - multihost only
        yield


# One cached jitted sentinel for step_timer's no-watch fallback. A fresh
# `jax.jit(lambda x: x + 1)` per call would be a NEW jit cache entry each
# time (lambda identity keys the cache), so every timed step would
# retrace — the drain itself would dirty the timing it exists to honor.
_sentinel_bump = None


def _bump_fn():
    global _sentinel_bump
    if _sentinel_bump is None:
        _sentinel_bump = jax.jit(lambda x: x + 1)
    return _sentinel_bump


class _TimerHandle:
    def __init__(self) -> None:
        self._watched: list[Any] = []

    def watch(self, tree: Any) -> Any:
        """Register outputs to block on before the clock stops (returns the
        tree for inline use: ``out = t.watch(step(...))``)."""
        self._watched.append(tree)
        return tree


@contextlib.contextmanager
def step_timer(
    result_holder: dict,
    key: str = "seconds",
    *,
    metric: str | None = None,
    registry: Any | None = None,
) -> Iterator[_TimerHandle]:
    """Time the enclosed block including async-dispatched device work.

    Register the block's outputs with ``handle.watch(out)`` so the timer
    blocks on them before stopping the clock (the ``MPI.Waitall!`` of
    timing). With nothing watched, a sentinel computation is enqueued per
    local device and blocked on — TPU executes programs in order per
    device, so this drains prior dispatched work.

    ``metric="train.step_seconds"`` additionally observes the elapsed
    time into a telemetry histogram of that name (on ``registry``, or the
    default :func:`fluxmpi_tpu.telemetry.get_registry` when omitted) —
    the bridge between this timing discipline and the metrics substrate.
    """
    handle = _TimerHandle()
    t0 = time.perf_counter()
    yield handle
    if handle._watched:
        jax.block_until_ready(handle._watched)
    else:
        import jax.numpy as jnp

        bump = _bump_fn()
        for d in jax.local_devices():
            bump(jax.device_put(jnp.zeros(()), d)).block_until_ready()
    elapsed = time.perf_counter() - t0
    result_holder[key] = elapsed
    if metric is not None:
        if registry is None:
            from ..telemetry import get_registry

            registry = get_registry()
        registry.histogram(metric).observe(elapsed)


def block_on(tree: Any) -> Any:
    """Block until every array in ``tree`` is ready (the timing analogue of
    ``MPI.Waitall!``, reference src/optimizer.jl:59). Returns the tree."""
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "block_until_ready"):
            leaf.block_until_ready()
    return tree
