"""Serving plane: continuous-batching inference with a paged KV cache.

The "millions of users" leg of the north star (ROADMAP open item 1):
:class:`InferenceEngine` turns the batch-level research decode API
(:func:`fluxmpi_tpu.models.generate`) into a traffic-serving loop —
request queue + token-budget admission control, an Orca-style
continuous-batching scheduler (new requests join the in-flight decode
batch between iterations, zero retrace), a vLLM-style block/paged KV
cache (:class:`BlockKVCache` — heterogeneous sequence lengths share
device memory through a free-list allocator and per-sequence block
tables), a prefill/decode phase split (prefill = ONE batched causal
forward via :func:`fluxmpi_tpu.models.generate.prefill_kv`), and
streaming token output with per-request latency accounting (TTFT,
per-token, queue wait) on the closed ``serving.*`` metric namespace.

The engine meets the rest of the production surface where it already
lives: ``serving.admit`` / ``serving.decode`` fault sites
(:mod:`fluxmpi_tpu.faults`), SIGTERM preemption draining (in-flight
requests finish, new admissions reject), the watchdog progress clock
(a stuck decode flips ``/healthz``), and a serving board on the live
exporter's ``/status`` (``scripts/fluxmpi_top.py`` renders it
fleet-wide). See docs/serving.md.
"""

from __future__ import annotations

from .cache import BlockKVCache, blocks_for_tokens  # noqa: F401
from .engine import (  # noqa: F401
    InferenceEngine,
    ServingConfig,
    ServingRequest,
    configure,
    enabled,
    get_engine,
    set_engine,
    shutdown,
)
from .observe import (  # noqa: F401
    RequestLog,
    RequestObserver,
    SLOBurnTracker,
    get_request_observer,
    set_request_observer,
)

__all__ = [
    "BlockKVCache",
    "blocks_for_tokens",
    "InferenceEngine",
    "ServingConfig",
    "ServingRequest",
    "RequestLog",
    "RequestObserver",
    "SLOBurnTracker",
    "configure",
    "enabled",
    "get_engine",
    "get_request_observer",
    "set_engine",
    "set_request_observer",
    "shutdown",
]
