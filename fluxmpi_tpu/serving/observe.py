"""Serving request-observability plane: lifecycle traces, the request
log, KV-pool forensics, and SLO burn accounting.

The serving engine's aggregate histograms (TTFT / per-token / queue
wait) answer "how is the service doing on average"; this plane answers
the questions an operator actually asks at 3am — *which* request was
slow, *why* was it rejected, *who* is holding the KV pool, and *how
fast* is the error budget burning. Orca and vLLM both argue scheduling
is only as good as per-iteration, per-request visibility; the ROADMAP's
fleet-serving follow-ups (router, preemption, prefix cache) are specced
to load-balance off exactly this substrate.

Three surfaces, one :class:`RequestObserver`:

- **Per-request lifecycle tracing** — every request that reaches a
  terminal state emits its span chain (``request.queue`` →
  ``request.prefill`` → ``request.decode`` → ``request.done`` /
  ``request.rejected``) onto the :mod:`~fluxmpi_tpu.telemetry.tracing`
  ring, each on its own virtual track (``request <id>``), so a
  Perfetto export — merged fleet-wide by ``scripts/merge_traces.py`` —
  renders a request timeline next to the engine's thread lanes. The
  terminal facts also land as one schema'd JSONL line
  (``fluxmpi_tpu.request/v1``: timings, token counts, reject/finish
  reason, KV blocks held, SLO verdict) in the :class:`RequestLog`;
  ``scripts/serving_report.py`` aggregates the log into a
  latency/SLO/reject post-mortem and
  ``scripts/check_metrics_schema.py`` validates every line.

- **KV-pool forensics** — :meth:`RequestObserver.kv_debug` snapshots
  the pool (occupancy, the process-lifetime high watermark, free-list
  fragmentation) plus a census of the top-N sequences by blocks held;
  on the first load-shed of a run (``queue_full``)
  :meth:`maybe_write_bundle` folds that census into an OOM-style debug
  bundle (``fluxmpi_serving.<process>.json`` — the watchdog-dump record
  with a ``serving`` section), so the artifact explaining *who ate the
  pool* exists before a human asks.

- **SLO burn accounting** — :class:`SLOBurnTracker` keeps good/total
  over a short and a long rolling window (the multi-window SRE burn
  pattern: alert only when BOTH windows burn, so a blip cannot page
  and a slow leak cannot hide). The engine feeds the min-across-windows
  rate to the anomaly plane's ``slo_burn`` rule (warn-default) and the
  per-window rates to the ``serving.slo_burn_rate{window=}`` gauges;
  the exporter's ``/status`` SERVING board and ``fluxmpi_top`` show the
  live burn next to p50/p99 TTFT and the top offenders.

Wiring follows the package convention: ``init(request_log=...)`` /
``FLUXMPI_TPU_REQUEST_LOG`` configure the plane (``1`` = on without a
file; a path = on + JSONL there, ``{process}`` formatted per host);
``FLUXMPI_TPU_SLO_WINDOW`` sets the long burn window in seconds.
Zero-cost-when-off (the PR 4 contract): the engine resolves
:func:`get_request_observer` once per run; with no observer installed
the per-request path reads one attribute and touches nothing else.
``telemetry.shutdown()`` resets the plane (log closed, burn tracker
cleared).
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
import warnings
from collections import deque
from typing import Any, Callable

from ..telemetry import tracing
from ..telemetry.registry import process_index_or_zero as _process_index
from ..telemetry.schema import REQUEST_SCHEMA

__all__ = [
    "RequestLog",
    "SLOBurnTracker",
    "RequestObserver",
    "get_request_observer",
    "set_request_observer",
    "configure",
    "shutdown",
]

_ENV_VAR = "FLUXMPI_TPU_REQUEST_LOG"
_ENV_WINDOW = "FLUXMPI_TPU_SLO_WINDOW"
_ENV_DIR = "FLUXMPI_TPU_ANOMALY_DIR"  # debug bundles share the anomaly dir

_DEFAULT_WINDOW = 300.0
# Long : short window ratio — the classic SRE pairing (1h/5m) scaled to
# a serving run's lifetime; both windows must burn for the alert.
_WINDOW_RATIO = 12.0
_DEFAULT_SLO_TARGET = 0.99

# Process-unique request ids: the track key every span/record carries.
_request_ids = itertools.count()


def next_request_id() -> int:
    """The next process-unique request id (monotonic, never reused —
    a request's Perfetto track and JSONL records key on it)."""
    return next(_request_ids)


def _env_window() -> float | None:
    """``FLUXMPI_TPU_SLO_WINDOW`` in seconds; garbage warns and falls
    back to the default (the env warn-and-degrade convention)."""
    raw = os.environ.get(_ENV_WINDOW)
    if raw is None or raw == "":
        return None
    try:
        val = float(raw)
    except ValueError:
        val = -1.0
    if val <= 0.0:
        warnings.warn(
            f"ignoring {_ENV_WINDOW}={raw!r}: must be a positive number "
            f"of seconds — the default window ({_DEFAULT_WINDOW:g}s) "
            f"stays in effect",
            stacklevel=3,
        )
        return None
    return val


class RequestLog:
    """Append-only JSONL sink for per-request terminal records.

    ``path`` may contain ``{process}`` (formatted with the process
    index — the multi-host spelling, like the trace export path). The
    file opens lazily on the first write and every line is flushed —
    a post-mortem after a crash must not lose the tail. Write failures
    warn once and count (:attr:`errors`); observability must never
    kill serving.
    """

    def __init__(self, path: str):
        self.path_spec = str(path)
        try:
            self.path = self.path_spec.format(process=_process_index())
        except (KeyError, IndexError, ValueError) as exc:
            raise ValueError(
                f"request log path {path!r} is not formattable: {exc!r} "
                f"(only a {{process}} placeholder is supported)"
            ) from None
        self._file: Any = None
        self._lock = threading.Lock()
        self.written = 0
        self.errors = 0

    def write(self, record: dict[str, Any]) -> None:
        with self._lock:
            try:
                if self._file is None:
                    parent = os.path.dirname(self.path)
                    if parent:
                        os.makedirs(parent, exist_ok=True)
                    self._file = open(self.path, "a", encoding="utf-8")
                self._file.write(
                    json.dumps(record, separators=(",", ":")) + "\n"
                )
                self._file.flush()
                self.written += 1
            except Exception as exc:
                self.errors += 1
                if self.errors == 1:
                    warnings.warn(
                        f"request log write to {self.path!r} failed: "
                        f"{exc!r}; further failures are counted silently",
                        stacklevel=3,
                    )

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                except Exception:
                    pass
                self._file = None


class SLOBurnTracker:
    """Multi-window rolling SLO burn rate (the SRE burn-alert shape).

    Every terminal request is one good/bad observation; ``bad`` means
    rejected or SLO-violating. The burn rate over a window is the bad
    fraction divided by the error budget (``1 - slo_target``): 1.0 =
    the budget is consumed exactly as fast as it accrues, >1 = the
    service will exhaust it. :meth:`alert_rate` is the MIN across the
    short and long windows — both must burn (multi-window AND), so a
    single slow request cannot page and a sustained regression cannot
    hide behind a long quiet average.

    Args:
      window: the long window in seconds (default
        ``FLUXMPI_TPU_SLO_WINDOW`` or 300); the short window is
        ``window / 12`` (the 1h/5m SRE ratio).
      slo_target: the good-fraction objective in (0, 1); the error
        budget is its complement.
      clock: monotonic time source (injectable for tests).
    """

    def __init__(
        self,
        *,
        window: float | None = None,
        slo_target: float = _DEFAULT_SLO_TARGET,
        clock: Callable[[], float] = time.monotonic,
    ):
        if window is None:
            window = _env_window() or _DEFAULT_WINDOW
        window = float(window)
        if window <= 0.0:
            raise ValueError(f"window must be > 0 seconds, got {window}")
        if not 0.0 < slo_target < 1.0:
            raise ValueError(
                f"slo_target must be in (0, 1), got {slo_target}"
            )
        self.windows: tuple[float, ...] = (window / _WINDOW_RATIO, window)
        self.slo_target = float(slo_target)
        self._clock = clock
        self._events: deque[tuple[float, bool]] = deque()
        self.good = 0
        self.total = 0

    @property
    def budget(self) -> float:
        return 1.0 - self.slo_target

    def observe(self, good: bool) -> None:
        now = self._clock()
        self._events.append((now, bool(good)))
        self.total += 1
        self.good += int(bool(good))
        horizon = now - self.windows[-1]
        while self._events and self._events[0][0] < horizon:
            self._events.popleft()

    def counts(self, window: float) -> tuple[int, int]:
        """``(good, total)`` inside the trailing ``window`` seconds."""
        cutoff = self._clock() - float(window)
        good = total = 0
        for t, g in reversed(self._events):
            if t < cutoff:
                break
            total += 1
            good += int(g)
        return good, total

    def burn_rate(self, window: float | None = None) -> float:
        """Bad fraction over the window divided by the error budget;
        0.0 with no data (an idle service burns nothing)."""
        good, total = self.counts(
            window if window is not None else self.windows[-1]
        )
        if total == 0:
            return 0.0
        return (1.0 - good / total) / self.budget

    def burn_rates(self) -> dict[float, float]:
        return {w: self.burn_rate(w) for w in self.windows}

    def alert_rate(self) -> float | None:
        """The multi-window alert value: the MIN burn rate across the
        windows, or None until every window has at least one
        observation (nothing to alert on)."""
        rates = []
        for w in self.windows:
            _, total = self.counts(w)
            if total == 0:
                return None
            rates.append(self.burn_rate(w))
        return min(rates)

    def reset(self) -> None:
        self._events.clear()
        self.good = 0
        self.total = 0


class RequestObserver:
    """The request-observability plane object the engine resolves once
    per run: terminal-record logging, span emission, burn tracking,
    offender accounting, and the KV debug bundle.

    Args:
      path: JSONL request-log path (``{process}`` formatted per host);
        None = no file log (spans/burn/forensics still on).
      log: a pre-built :class:`RequestLog` (overrides ``path``).
      slo_window / slo_target: burn-tracker knobs (see
        :class:`SLOBurnTracker`).
      top_offenders: how many worst-TTFT requests / biggest block
        holders the board and census carry.
      dump_dir: where the serving debug bundle lands (default
        ``FLUXMPI_TPU_ANOMALY_DIR`` or ``.`` — the bundle family
        shares the anomaly plane's directory).
      dump: write bundles at all.
      clock: burn-tracker time source (injectable for tests).
    """

    def __init__(
        self,
        *,
        path: str | None = None,
        log: RequestLog | None = None,
        slo_window: float | None = None,
        slo_target: float = _DEFAULT_SLO_TARGET,
        top_offenders: int = 5,
        dump_dir: str | None = None,
        dump: bool = True,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.log = log if log is not None else (
            RequestLog(path) if path else None
        )
        self.burn = SLOBurnTracker(
            window=slo_window, slo_target=slo_target, clock=clock
        )
        self.enabled = True
        self.top_offenders = int(top_offenders)
        self.dump_dir = (
            dump_dir if dump_dir is not None
            else os.environ.get(_ENV_DIR, ".")
        )
        self.dump = dump
        self.records = 0
        self.last_dump_path: str | None = None
        self._dumped = False
        self._lock = threading.Lock()
        # Rolling TTFT sample for the board's p50/p99 (bounded — the
        # registry histogram owns the exact cumulative buckets).
        self._ttfts: deque[float] = deque(maxlen=512)
        self._offenders: list[tuple[float, int]] = []  # (ttft, id), worst first

    # -- terminal records ----------------------------------------------

    def build_record(
        self,
        req: Any,
        *,
        kv_blocks: int = 0,
        violations: tuple[str, ...] = (),
    ) -> dict[str, Any]:
        """One ``fluxmpi_tpu.request/v1`` record from a terminal
        request handle (see :func:`...telemetry.schema.validate_request_record`)."""
        status = "finished" if req.status == "finished" else "rejected"
        total_s = (
            req.finished_t - req.submitted_t
            if req.finished_t is not None else None
        )
        return {
            "schema": REQUEST_SCHEMA,
            "time_unix": time.time(),
            "process": _process_index(),
            "request_id": int(req.id),
            "status": status,
            "reason": req.reject_reason,
            "prompt_tokens": int(req.prompt.shape[0]),
            "output_tokens": len(req.tokens),
            "kv_blocks": int(kv_blocks),
            "queue_wait_s": req.queue_wait_s,
            "ttft_s": req.ttft_s,
            "per_token_s": req.per_token_s,
            "total_s": total_s,
            "slo_ok": bool(status == "finished" and not violations),
            "slo_violations": list(violations),
        }

    def observe_terminal(
        self,
        req: Any,
        *,
        kv_blocks: int = 0,
        violations: tuple[str, ...] = (),
    ) -> dict[str, Any]:
        """Bank one request's terminal transition: JSONL record, span
        chain, burn observation, offender accounting. Called by the
        engine exactly once per request (finish, reject, or drain)."""
        record = self.build_record(
            req, kv_blocks=kv_blocks, violations=violations
        )
        with self._lock:
            self.records += 1
            if req.ttft_s is not None:
                self._ttfts.append(float(req.ttft_s))
                self._offenders.append((float(req.ttft_s), int(req.id)))
                self._offenders.sort(reverse=True)
                del self._offenders[self.top_offenders:]
        self.burn.observe(record["slo_ok"])
        if self.log is not None:
            self.log.write(record)
        self._emit_spans(req, record)
        return record

    def _emit_spans(self, req: Any, record: dict[str, Any]) -> None:
        """The lifecycle span chain, one virtual track per request.
        Stamps are ``perf_counter`` seconds (the engine clock), exactly
        what :meth:`Tracer.add_complete_event` rebases at export."""
        tracer = tracing.get_tracer()
        if not tracer.enabled:
            return
        rid = int(req.id)
        tracer.name_track(rid, f"request {rid}")
        end = req.finished_t if req.finished_t is not None else req._clock()
        queue_end = req.admitted_t if req.admitted_t is not None else end
        tracer.add_complete_event(
            "request.queue", req.submitted_t, queue_end,
            track=rid, request_id=rid,
        )
        if req.admitted_t is not None:
            prefill_end = (
                req.first_token_t if req.first_token_t is not None else end
            )
            tracer.add_complete_event(
                "request.prefill", req.admitted_t, prefill_end,
                track=rid, request_id=rid,
                prompt_tokens=record["prompt_tokens"],
            )
            if req.first_token_t is not None:
                tracer.add_complete_event(
                    "request.decode", req.first_token_t, end,
                    track=rid, request_id=rid,
                    tokens=record["output_tokens"],
                )
        if record["status"] == "finished":
            tracer.instant(
                "request.done", track=rid, request_id=rid,
                slo_ok=record["slo_ok"],
            )
        else:
            tracer.instant(
                "request.rejected", track=rid, request_id=rid,
                reason=record["reason"] or "",
            )

    # -- board / percentiles -------------------------------------------

    def ttft_percentiles(self) -> tuple[float | None, float | None]:
        """(p50, p99) over the rolling TTFT sample (None with no data)."""
        with self._lock:
            data = sorted(self._ttfts)
        if not data:
            return None, None

        def pct(p: float) -> float:
            return data[min(len(data) - 1, int(p * (len(data) - 1) + 0.5))]

        return pct(0.50), pct(0.99)

    def top_offender_list(self) -> list[dict[str, Any]]:
        with self._lock:
            return [
                {"request_id": rid, "ttft_s": t}
                for t, rid in self._offenders
            ]

    def board(self) -> dict[str, Any]:
        """The SERVING status-board fields this plane contributes (the
        engine merges them into ``note_serving``)."""
        p50, p99 = self.ttft_percentiles()
        rates = self.burn.burn_rates()
        return {
            "burn_rate": max(rates.values()) if rates else 0.0,
            "burn_windows": {f"{w:g}": r for w, r in rates.items()},
            "ttft_p50": p50,
            "ttft_p99": p99,
            "top_offenders": self.top_offender_list(),
            "requests_logged": self.records,
        }

    # -- KV-pool forensics ---------------------------------------------

    def kv_debug(self, engine: Any) -> dict[str, Any]:
        """Pool forensics snapshot: occupancy, high watermark,
        fragmentation, and the census of the top-N sequences by blocks
        held (engine-side — the cache does not map blocks to
        sequences, the slots do)."""
        cache = engine.cache
        census = []
        for slot in engine._slots:
            if slot is None:
                continue
            census.append(
                {
                    "request_id": int(slot.req.id),
                    "blocks": len(slot.blocks),
                    "position": int(slot.position),
                    "generated": int(slot.generated),
                }
            )
        census.sort(key=lambda e: (-e["blocks"], e["request_id"]))
        total = cache.num_blocks - 1
        return {
            "blocks_total": total,
            "blocks_in_use": cache.used_blocks,
            "blocks_free": cache.free_blocks,
            "high_watermark_blocks": cache.high_watermark_blocks,
            "fragmentation": cache.fragmentation,
            # NOT engine.queue_depth: that property takes the engine
            # lock, and the queue_full bundle trigger fires from
            # _reject UNDER submit's lock — a torn len() is fine for
            # forensics, a deadlock is not.
            "queue_depth": len(engine._queue),
            "census": census[: self.top_offenders],
            "burn_rates": {
                f"{w:g}": r for w, r in self.burn.burn_rates().items()
            },
        }

    def dump_path(self) -> str:
        return os.path.join(
            self.dump_dir or ".",
            f"fluxmpi_serving.{_process_index()}.json",
        )

    def write_bundle(self, engine: Any, trigger: str) -> str:
        """Write the OOM-style serving debug bundle and return its
        path: the watchdog-dump record (thread stacks, flight-recorder
        tail, open spans, registry flush) with a ``serving`` section —
        the pool census — attached, so triage tooling for hang dumps
        reads it unchanged."""
        from ..telemetry.watchdog import Watchdog, get_watchdog

        wd = get_watchdog()
        if wd is None:
            # An unarmed builder: build_dump never starts threads or
            # installs signals — it only assembles the record.
            wd = Watchdog(deadline=1.0)
        record = wd.build_dump(f"serving:{trigger}")
        record["serving"] = self.kv_debug(engine)
        path = self.dump_path()
        os.makedirs(self.dump_dir or ".", exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            json.dump(record, f, indent=1)
        self.last_dump_path = path
        return path

    def maybe_write_bundle(self, engine: Any, trigger: str) -> str | None:
        """Rate-limited bundle write (once per observer lifetime): the
        first load-shed explains the pool, later ones repeat it."""
        if not self.dump or self._dumped:
            return None
        self._dumped = True
        try:
            return self.write_bundle(engine, trigger)
        except Exception as exc:  # diagnostics must never kill serving
            warnings.warn(
                f"serving debug bundle write failed: {exc!r}",
                stacklevel=3,
            )
            return None

    # -- lifecycle -----------------------------------------------------

    def close(self) -> None:
        """Full reset: log closed, burn tracker and samples cleared —
        the fault-plane leak rule (``telemetry.shutdown()`` path)."""
        self.enabled = False
        if self.log is not None:
            self.log.close()
        self.burn.reset()
        with self._lock:
            self._ttfts.clear()
            self._offenders.clear()


# ---------------------------------------------------------------------------
# Plane wiring (init kwarg / env var)
# ---------------------------------------------------------------------------

_active: RequestObserver | None = None
_active_lock = threading.Lock()


def get_request_observer() -> RequestObserver | None:
    """The installed observer, if any (None = plane off)."""
    return _active


def set_request_observer(
    observer: RequestObserver | None,
) -> RequestObserver | None:
    """Install (or, with None, remove) the process request observer;
    returns the previous one."""
    global _active
    with _active_lock:
        prev, _active = _active, observer
    return prev


def configure(spec: Any = None) -> RequestObserver | None:
    """Wire the request-observability plane from a one-value spec
    (mirror of :func:`fluxmpi_tpu.telemetry.configure`):

    - ``None`` — read ``FLUXMPI_TPU_REQUEST_LOG`` (same forms; no-op
      when unset/empty);
    - ``False`` / ``"0"`` — uninstall (log closed, burn cleared);
    - ``True`` / ``"1"`` — install with no file log (spans, burn
      accounting, and forensics still on);
    - any other string — install logging terminal records to that JSONL
      path (``{process}`` formatted with the process index);
    - a :class:`RequestObserver` — install it.

    Called by ``fluxmpi_tpu.init(request_log=...)``; idempotent — an
    installed observer is kept (with its burn windows) on a replay with
    an equivalent spec. A malformed env path warns and degrades;
    the same mistake made programmatically raises.
    """
    from_env = spec is None
    if spec is None:
        spec = os.environ.get(_ENV_VAR)
        if spec is None or spec == "":
            return _active
    if isinstance(spec, RequestObserver):
        if _active is not None and _active is not spec:
            _active.close()
        spec.enabled = True
        set_request_observer(spec)
        return spec
    if spec is False or spec == "0":
        shutdown()
        return None
    if spec is True or spec == "1":
        if _active is not None:
            _active.enabled = True
            return _active
        obs = RequestObserver()
        set_request_observer(obs)
        return obs
    if isinstance(spec, str):
        if (
            _active is not None
            and _active.log is not None
            and _active.log.path_spec == spec
        ):
            _active.enabled = True
            return _active
        try:
            obs = RequestObserver(path=spec)
        except ValueError as exc:
            if from_env:
                warnings.warn(
                    f"ignoring {_ENV_VAR}={spec!r}: {exc} — the request "
                    f"log stays off",
                    stacklevel=2,
                )
                return _active
            raise
        if _active is not None:
            _active.close()
        set_request_observer(obs)
        return obs
    raise ValueError(
        f"request_log spec must be a bool, '0'/'1', a path, or a "
        f"RequestObserver; got {spec!r}"
    )


def shutdown() -> None:
    """Reset the plane: close the request log, clear the burn tracker,
    uninstall — state left armed would leak into the next init cycle
    (the fault-plane leak rule)."""
    obs = set_request_observer(None)
    if obs is not None:
        try:
            obs.close()
        except Exception:
            pass
