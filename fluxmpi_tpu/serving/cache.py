"""Block/paged KV cache: heterogeneous sequence lengths share device HBM.

The research-API decode path (:mod:`fluxmpi_tpu.models.generate`)
allocates one contiguous ``[batch, max_len]`` KV cache per call — every
sequence pays for the longest possible one. A serving engine cannot: a
mixed workload of 8-token and 500-token requests sharing per-request
max-len rows wastes most of the pool. :class:`BlockKVCache` is the
vLLM-style answer scaled to this repo: the flax decode cache's
``[*, max_len, heads, head_dim]`` axis is cut into fixed-size **blocks**,

- the physical pool is ``[num_layers, num_blocks, block_size, heads,
  head_dim]`` per K and V (device-resident, donated through the decode
  step so it updates in place);
- a **free-list allocator** hands blocks to sequences at admission and
  takes them back at eviction — a freed block is immediately reusable
  by the next request (the free-list round-trip the serving tests
  assert);
- each sequence carries a **block table** (``[max_blocks_per_seq]``
  int32 row): logical position ``p`` of the sequence lives at pool slot
  ``(table[p // block_size], p % block_size)``. The decode step gathers
  a sequence's blocks into the contiguous layout the flax decode twin
  expects and scatters the newly written position back (see
  :mod:`fluxmpi_tpu.serving.engine`).

**Block 0 is the trash block**: it is never allocated. Unused table
entries point at it, masked prefill positions and idle batch slots
write into it, and attention's cache-index mask zeroes anything read
from it — so padding and inactive slots need no special-case shapes.

Admission is **token-budget based**: a request reserves its worst-case
``ceil((prompt + max_new_tokens) / block_size)`` blocks up front, so an
admitted request can never strand mid-decode out of pool (the simple,
preemption-free contract; lazy growth + sequence preemption is the
follow-up documented in docs/serving.md). :meth:`fits_device` checks
the pool's byte footprint against the PR 9 memory plane's
``bytes_limit`` before any device allocation happens — an engine that
would OOM the chip refuses at construction, not at the first admission.
"""

from __future__ import annotations

from typing import Any

__all__ = ["BlockKVCache", "blocks_for_tokens"]

TRASH_BLOCK = 0


def blocks_for_tokens(tokens: int, block_size: int) -> int:
    """Blocks needed to hold ``tokens`` cache positions."""
    return -(-int(tokens) // int(block_size))


class BlockKVCache:
    """Paged K/V pool + free-list allocator + per-sequence block tables.

    Args:
      num_layers, num_heads, head_dim: the model's cache geometry
        (``head_dim = qkv_features // num_heads``).
      num_blocks: total pool blocks INCLUDING the reserved trash block
        (capacity = ``(num_blocks - 1) * block_size`` tokens).
      block_size: cache positions per block.
      max_blocks_per_seq: width of a block-table row — the longest
        sequence the engine serves, in blocks.
      dtype: pool dtype (the model's cache dtype).

    The pools are created lazily on first :attr:`k_pool` access (so the
    allocator half is importable/testable without a device) and live as
    plain device arrays the engine threads through its jitted steps.
    """

    def __init__(
        self,
        *,
        num_layers: int,
        num_heads: int,
        head_dim: int,
        num_blocks: int,
        block_size: int,
        max_blocks_per_seq: int,
        dtype: Any = None,
    ):
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block 0 is the reserved trash "
                f"block), got {num_blocks}"
            )
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        if max_blocks_per_seq < 1:
            raise ValueError(
                f"max_blocks_per_seq must be >= 1, got {max_blocks_per_seq}"
            )
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.max_blocks_per_seq = int(max_blocks_per_seq)
        self._dtype = dtype
        # LIFO free list: the most recently freed block is handed out
        # next — the round-trip the reuse test pins down.
        self._free: list[int] = list(range(self.num_blocks - 1, 0, -1))
        # Forensics (PR 16): the pool-lifetime peak of used_blocks —
        # "how close did this run actually get to the wall".
        self._high_watermark = 0
        self._k_pool = None
        self._v_pool = None

    # -- allocator -----------------------------------------------------

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return (self.num_blocks - 1) - len(self._free)

    @property
    def capacity_tokens(self) -> int:
        """Total cache positions the allocatable pool holds."""
        return (self.num_blocks - 1) * self.block_size

    @property
    def free_tokens(self) -> int:
        return len(self._free) * self.block_size

    @property
    def high_watermark_blocks(self) -> int:
        """Pool-lifetime peak of :attr:`used_blocks` (updated at every
        allocation) — the occupancy forensics gauge."""
        return self._high_watermark

    @property
    def fragmentation(self) -> float:
        """Free-list scatter in [0, 1]: ``1 - (longest contiguous free
        run / free blocks)``; 0.0 when the free space is one run (or
        empty). Block allocation is id-agnostic, so this never blocks
        an admission — it measures how shuffled churn has left the
        pool, the precursor signal for block-coalescing / prefix-cache
        work that DOES care about contiguity."""
        if not self._free:
            return 0.0
        ids = sorted(self._free)
        longest = run = 1
        for a, b in zip(ids, ids[1:]):
            run = run + 1 if b == a + 1 else 1
            if run > longest:
                longest = run
        return 1.0 - longest / len(ids)

    def blocks_for(self, tokens: int) -> int:
        return blocks_for_tokens(tokens, self.block_size)

    def can_alloc(self, tokens: int) -> bool:
        return self.blocks_for(tokens) <= len(self._free)

    def alloc(self, tokens: int) -> list[int]:
        """Reserve the blocks for ``tokens`` cache positions; raises
        ``RuntimeError`` when the pool cannot cover them (callers gate
        on :meth:`can_alloc` — admission control, not this, is where
        "no" is decided)."""
        need = self.blocks_for(tokens)
        if need > len(self._free):
            raise RuntimeError(
                f"KV pool exhausted: need {need} blocks for {tokens} "
                f"tokens, {len(self._free)} free"
            )
        if need > self.max_blocks_per_seq:
            raise ValueError(
                f"{tokens} tokens need {need} blocks but block tables are "
                f"{self.max_blocks_per_seq} wide"
            )
        blocks = [self._free.pop() for _ in range(need)]
        if self.used_blocks > self._high_watermark:
            self._high_watermark = self.used_blocks
        return blocks

    def free(self, blocks: list[int]) -> None:
        """Return a sequence's blocks to the pool (eviction)."""
        for b in blocks:
            if not 0 < b < self.num_blocks:
                raise ValueError(f"block id {b} outside the pool")
            if b in self._free:
                raise ValueError(f"double free of block {b}")
        self._free.extend(blocks)

    def table_row(self, blocks: list[int]):
        """``[max_blocks_per_seq]`` int32 block-table row for a
        sequence's blocks; unused entries point at the trash block."""
        import numpy as np

        row = np.full((self.max_blocks_per_seq,), TRASH_BLOCK, np.int32)
        row[: len(blocks)] = blocks
        return row

    # -- device pools --------------------------------------------------

    @property
    def pool_shape(self) -> tuple[int, ...]:
        return (
            self.num_layers,
            self.num_blocks,
            self.block_size,
            self.num_heads,
            self.head_dim,
        )

    @property
    def pool_bytes(self) -> int:
        """Byte footprint of BOTH pools (K and V)."""
        import numpy as np

        import jax.numpy as jnp

        dtype = self._dtype if self._dtype is not None else jnp.float32
        itemsize = np.dtype(dtype).itemsize
        n = 1
        for d in self.pool_shape:
            n *= d
        return 2 * n * itemsize

    def _ensure_pools(self) -> None:
        if self._k_pool is None:
            import jax.numpy as jnp

            dtype = self._dtype if self._dtype is not None else jnp.float32
            self._k_pool = jnp.zeros(self.pool_shape, dtype)
            self._v_pool = jnp.zeros(self.pool_shape, dtype)

    @property
    def k_pool(self):
        self._ensure_pools()
        return self._k_pool

    @k_pool.setter
    def k_pool(self, value) -> None:
        self._k_pool = value

    @property
    def v_pool(self):
        self._ensure_pools()
        return self._v_pool

    @v_pool.setter
    def v_pool(self, value) -> None:
        self._v_pool = value

    def drop_pools(self) -> None:
        """Release the device arrays (engine shutdown — the pool must
        not outlive the engine into the next init cycle)."""
        self._k_pool = None
        self._v_pool = None

    # -- memory-plane admission check ----------------------------------

    def fits_device(self, device: Any = None) -> tuple[bool, str]:
        """OOM-safe construction check against the PR 9 memory plane:
        would the pool's byte footprint fit the device's remaining HBM?
        Returns ``(fits, detail)``; backends without memory stats (CPU)
        report ``(True, "no device memory stats")`` — there is nothing
        to check against, and host memory is the OS's problem."""
        from ..telemetry.memory import device_memory_stats

        if device is None:
            import jax

            device = jax.local_devices()[0]
        stats = device_memory_stats(device)
        limit = stats.get("bytes_limit")
        if not limit:
            return True, "no device memory stats"
        in_use = stats.get("bytes_in_use", 0.0)
        need = float(self.pool_bytes)
        fits = in_use + need <= limit
        return fits, (
            f"pool {need / 2**20:.1f} MiB + in-use {in_use / 2**20:.1f} "
            f"MiB vs limit {limit / 2**20:.1f} MiB"
        )
