"""Continuous-batching inference engine over the TransformerLM decode twin.

The serving plane's core loop (ROADMAP open item 1 — the "millions of
users" leg): an Orca-style **continuous-batching** scheduler where new
requests join the in-flight decode batch *between* iterations, built
from the pieces this repo already has — the decode twin of
:mod:`fluxmpi_tpu.models.generate`, the batched prefill kernel
(:func:`~fluxmpi_tpu.models.generate.prefill_kv`), the paged
:class:`~fluxmpi_tpu.serving.cache.BlockKVCache`, the ``serving.*``
telemetry namespace, the watchdog's progress clock (``/healthz`` covers
a stuck decode), and the fault plane (``serving.admit`` /
``serving.decode`` chaos sites, SIGTERM drain).

Phase split:

- **prefill** — one batched causal forward per admission writes the
  whole prompt's K/V into the request's pool blocks and yields the
  first generated token (TTFT = one forward, not O(prompt) ticks).
  Prefill programs are compiled per *prompt bucket* (prompt length
  rounded up to a block multiple) — a handful of shapes, warmed by
  :meth:`InferenceEngine.warmup`.
- **decode** — ONE fixed-shape jitted step per engine iteration runs
  every active batch slot one token forward: gather each slot's blocks
  into the contiguous cache layout the flax decode twin expects, run
  the twin per slot (vmapped, so every slot carries its *own* cache
  index/position — heterogeneous sequence states in one dispatch),
  scatter the newly written K/V position back into the pool, and
  return the argmax tokens. Shapes depend only on the engine geometry
  ``(slots, max_blocks_per_seq, block_size)`` — never on which
  requests are active — so **requests join and leave the batch with
  zero retrace** (the compile monitor asserts this in the tests and
  the bench).

The decode loop is **host-driven** (``lax.scan``-free): one dispatch +
one small device→host token transfer per iteration, with eviction,
admission, streaming delivery, and preemption polling between
iterations — the same boundary discipline as ``train_loop``'s dispatch
loop, including the PR 4 zero-cost instrumentation contract (the
registry/exporter are resolved ONCE per run; fully-off pays no
per-token clock reads or handle lookups beyond the per-request
latency stamps that are the serving API itself).

Wiring follows the package convention: ``init(serving=...)`` /
``FLUXMPI_TPU_SERVING`` (+ ``_SLOTS`` / ``_BLOCK_SIZE`` / ``_BLOCKS`` /
``_QUEUE``) set fleet defaults via :func:`configure`;
``telemetry.shutdown()`` resets the plane (engine stopped, pools
dropped — the fault-plane leak rule). See docs/serving.md.
"""

from __future__ import annotations

import os
import queue as queue_mod
import threading
import time
import warnings
from collections import deque
from typing import Any, Callable

import numpy as np

from ..errors import RequestRejectedError
from ..telemetry.registry import MetricsRegistry, get_registry
from . import observe as _observe_mod
from .cache import BlockKVCache, TRASH_BLOCK, blocks_for_tokens

__all__ = [
    "InferenceEngine",
    "ServingRequest",
    "ServingConfig",
    "get_engine",
    "set_engine",
    "configure",
    "shutdown",
    "enabled",
]

_ENV_ON = "FLUXMPI_TPU_SERVING"
_ENV_SLOTS = "FLUXMPI_TPU_SERVING_SLOTS"
_ENV_BLOCK_SIZE = "FLUXMPI_TPU_SERVING_BLOCK_SIZE"
_ENV_BLOCKS = "FLUXMPI_TPU_SERVING_BLOCKS"
_ENV_QUEUE = "FLUXMPI_TPU_SERVING_QUEUE"
_ENV_ATTENTION = "FLUXMPI_TPU_SERVING_ATTENTION"

_DEFAULT_SLOTS = 8
_DEFAULT_BLOCK_SIZE = 16
_DEFAULT_MAX_QUEUE = 64


def _env_int(name: str) -> int | None:
    """An int env knob; garbage warns and falls back to None — the ONE
    shared warn-and-default parser (``config.env_int``)."""
    from ..config import env_int

    return env_int(name)


class ServingConfig:
    """Fleet defaults for engine geometry (``init(serving=...)`` /
    ``FLUXMPI_TPU_SERVING_*``). ``None`` fields defer to the env var,
    then the built-in default, at engine construction."""

    def __init__(
        self,
        *,
        slots: int | None = None,
        block_size: int | None = None,
        num_blocks: int | None = None,
        max_queue: int | None = None,
        attention: str | None = None,
    ):
        self.slots = slots
        self.block_size = block_size
        self.num_blocks = num_blocks
        self.max_queue = max_queue
        self.attention = attention


_config: ServingConfig | None = None
_active_engine: "InferenceEngine | None" = None
_active_lock = threading.Lock()


def get_engine() -> "InferenceEngine | None":
    """The registered engine, if any (the last one constructed; None =
    plane off)."""
    return _active_engine


def set_engine(engine: "InferenceEngine | None") -> "InferenceEngine | None":
    """Register (or, with None, remove) the process engine; returns the
    previous one."""
    global _active_engine
    with _active_lock:
        prev, _active_engine = _active_engine, engine
    return prev


def enabled() -> bool:
    """Whether ``init(serving=...)`` / ``FLUXMPI_TPU_SERVING`` marked
    the plane configured (engine construction never requires it — this
    is the fleet-defaults switch)."""
    return _config is not None


def configure(spec: Any = None) -> ServingConfig | None:
    """Wire serving fleet defaults from a one-value spec (the
    :func:`fluxmpi_tpu.telemetry.configure` shape):

    - ``None`` — read ``FLUXMPI_TPU_SERVING`` (no-op when unset/empty);
    - ``False`` / ``"0"`` — reset the plane (stop + deregister any
      running engine, drop the defaults);
    - ``True`` / ``"1"`` — enable with env-derived geometry
      (``FLUXMPI_TPU_SERVING_SLOTS`` / ``_BLOCK_SIZE`` / ``_BLOCKS`` /
      ``_QUEUE``);
    - a dict — enable with those geometry overrides (same keys as
      :class:`ServingConfig`);
    - a :class:`ServingConfig` — install it.

    Called by ``fluxmpi_tpu.init(serving=...)``, idempotent replays
    included.
    """
    global _config
    from_env = spec is None
    if spec is None:
        spec = os.environ.get(_ENV_ON)
        if spec is None or spec == "":
            return _config
    if spec is False or spec == "0":
        shutdown()
        return None
    if isinstance(spec, ServingConfig):
        _config = spec
        return _config
    if spec is True or spec == "1":
        _config = ServingConfig()
        return _config
    if isinstance(spec, dict):
        unknown = set(spec) - {
            "slots", "block_size", "num_blocks", "max_queue", "attention",
        }
        if unknown:
            raise ValueError(
                f"unknown serving config keys {sorted(unknown)}; expected "
                f"slots/block_size/num_blocks/max_queue/attention"
            )
        _config = ServingConfig(**spec)
        return _config
    message = (
        f"serving spec must be a bool, '0'/'1', a dict, or a "
        f"ServingConfig; got {spec!r}"
    )
    if from_env:
        # The export-plane convention: an env typo (FLUXMPI_TPU_SERVING=
        # "true") degrades with a warning instead of crashing every
        # init() of a job that may never even serve.
        warnings.warn(
            f"ignoring {_ENV_ON}={spec!r}: {message} — the serving "
            f"plane defaults stay unset",
            stacklevel=2,
        )
        return _config
    raise ValueError(message)


def shutdown() -> None:
    """Reset the serving plane: stop and deregister the engine (serve
    thread joined, queued/active requests failed, KV pools dropped) and
    clear the configured defaults — state left armed would leak into
    the next init cycle (the fault-plane leak rule).
    ``telemetry.shutdown()`` calls this before tearing down the planes
    the engine posts into."""
    global _config
    engine = set_engine(None)
    if engine is not None:
        close = getattr(engine, "close", None)
        if close is not None:
            try:
                close()
            except Exception:
                pass
    _config = None


def _resolve(explicit: int | None, configured: int | None,
             env_name: str, default: int) -> int:
    if explicit is not None:
        return int(explicit)
    if configured is not None:
        return int(configured)
    env = _env_int(env_name)
    if env is not None:
        return env
    return default


# ---------------------------------------------------------------------------
# Requests
# ---------------------------------------------------------------------------

QUEUED = "queued"
ACTIVE = "active"
FINISHED = "finished"
REJECTED = "rejected"


class ServingRequest:
    """One submitted generation request: prompt in, streamed tokens out.

    The handle the engine returns from :meth:`InferenceEngine.submit`.
    Tokens arrive three ways as decode progresses: the ``on_token``
    callback (fired from the engine thread — keep it cheap), the
    :meth:`stream` iterator (a bounded queue the consumer drains from
    any thread), and the accumulated :attr:`tokens` list. Latency
    accounting rides the handle: :attr:`queue_wait_s` (submit →
    admission), :attr:`ttft_s` (submit → first token), and
    :attr:`per_token_s` (mean inter-token time after the first).
    """

    def __init__(
        self,
        prompt: np.ndarray,
        max_new_tokens: int,
        *,
        eos_token: int | None = None,
        on_token: Callable[[int], None] | None = None,
        clock: Callable[[], float] = time.perf_counter,
    ):
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        # Process-unique id: the request-observability plane's track
        # key (Perfetto lane, JSONL record, census attribution).
        self.id = _observe_mod.next_request_id()
        self.eos_token = eos_token
        self.on_token = on_token
        self.tokens: list[int] = []
        self.status = QUEUED
        self.reject_reason: str | None = None
        self._clock = clock
        self.submitted_t = clock()
        self.admitted_t: float | None = None
        self.first_token_t: float | None = None
        self.finished_t: float | None = None
        self._done = threading.Event()
        self._stream: queue_mod.SimpleQueue = queue_mod.SimpleQueue()

    # -- consumer side -------------------------------------------------

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: float | None = None) -> bool:
        """Block until the request finishes (or is rejected)."""
        return self._done.wait(timeout)

    def result(self, timeout: float | None = None) -> np.ndarray:
        """The full sequence (prompt + generated tokens) once finished;
        raises :class:`~fluxmpi_tpu.errors.RequestRejectedError` (a
        ``RuntimeError`` carrying ``reject_reason``) for rejected
        requests."""
        if not self.wait(timeout):
            raise TimeoutError("request still in flight")
        if self.status == REJECTED:
            raise RequestRejectedError(self.reject_reason)
        return np.concatenate(
            [self.prompt, np.asarray(self.tokens, np.int32)]
        )

    def stream(self, timeout: float | None = None):
        """Yield generated tokens as the engine produces them (ends at
        completion; raises
        :class:`~fluxmpi_tpu.errors.RequestRejectedError` on rejection
        and ``TimeoutError`` when ``timeout`` seconds pass without a
        token — the same exception :meth:`result` uses, not the
        internal queue's). Drive the engine from another thread
        (:meth:`InferenceEngine.start`) or interleave with
        :meth:`InferenceEngine.step` calls."""
        while True:
            try:
                tok = self._stream.get(timeout=timeout)
            except queue_mod.Empty:
                raise TimeoutError(
                    f"no token within {timeout} seconds"
                ) from None
            if tok is None:
                if self.status == REJECTED:
                    raise RequestRejectedError(self.reject_reason)
                return
            yield tok

    # -- latency accounting --------------------------------------------

    @property
    def queue_wait_s(self) -> float | None:
        if self.admitted_t is None:
            return None
        return self.admitted_t - self.submitted_t

    @property
    def ttft_s(self) -> float | None:
        if self.first_token_t is None:
            return None
        return self.first_token_t - self.submitted_t

    @property
    def per_token_s(self) -> float | None:
        """Mean inter-token latency after the first token (None until
        finished or with a single generated token)."""
        if self.finished_t is None or self.first_token_t is None:
            return None
        n = len(self.tokens)
        if n < 2:
            return None
        return (self.finished_t - self.first_token_t) / (n - 1)

    # -- engine side ---------------------------------------------------

    def _deliver(self, token: int) -> None:
        if self.first_token_t is None:
            self.first_token_t = self._clock()
        self.tokens.append(int(token))
        self._stream.put(int(token))
        if self.on_token is not None:
            try:
                self.on_token(int(token))
            except Exception as exc:
                warnings.warn(
                    f"serving on_token callback raised {exc!r}; token "
                    f"delivery continues",
                    stacklevel=2,
                )

    def _finish(self, status: str, reason: str | None = None) -> None:
        self.status = status
        self.reject_reason = reason
        self.finished_t = self._clock()
        self._stream.put(None)
        self._done.set()


class _Slot:
    """One active batch slot: the request plus its device-side cursor."""

    __slots__ = ("req", "blocks", "table", "position", "last_token",
                 "generated")

    def __init__(self, req: ServingRequest, blocks: list[int],
                 table: np.ndarray):
        self.req = req
        self.blocks = blocks
        self.table = table
        # Cache positions filled so far == the position the NEXT fed
        # token occupies; after prefill this is the prompt length.
        self.position = 0
        self.last_token = 0
        self.generated = 0


# ---------------------------------------------------------------------------
# Engine
# ---------------------------------------------------------------------------


class InferenceEngine:
    """Continuous-batching inference engine with a paged KV cache.

    Args:
      model: a :class:`~fluxmpi_tpu.models.TransformerLM` (training
        configuration — the decode twin is derived internally, exactly
        like :func:`~fluxmpi_tpu.models.generate`).
      params: its variables (``{"params": ...}``).
      slots: static decode batch width (default: ``init(serving=)`` /
        ``FLUXMPI_TPU_SERVING_SLOTS`` / 8). The decode step's shapes
        are fixed by this — joins/evictions never retrace.
      block_size: KV cache positions per pool block (default env /16).
      num_blocks: pool size in blocks, including the reserved trash
        block (default env / ``1 + slots * max_len/block_size`` — no
        oversubscription; size it DOWN to make admission control bite).
      max_queue: queued (admitted-later) requests past which
        :meth:`submit` load-sheds with a rejection (default env / 64).
      max_len: per-sequence cap on ``prompt + max_new_tokens`` (default
        ``model.max_len`` rounded down to a block multiple).
      continuous: True (default) = requests join the decode batch
        between any two iterations; False = static batching (a new
        group is admitted only when every slot has drained — the A/B
        baseline ``bench.py --child serving`` measures against).
      slo_ttft_s / slo_token_s: optional latency objectives; completions
        breaching them bump ``serving.slo_violations{kind=...}``.
      registry: metrics registry (default: the process-global one,
        resolved once per run — the zero-cost contract).
      clock: time source for latency accounting (injectable for tests).
      check_memory: verify the pool's byte footprint against the memory
        plane's device ``bytes_limit`` before allocating (raises
        ``RuntimeError`` when it cannot fit — OOM-safe admission starts
        at construction).
      attention: ``"flash"``/``"naive"``/``"auto"`` overrides the
        model's kernel-plane switch for prefill and the paged decode
        step (default: ``init(serving=)`` /
        ``FLUXMPI_TPU_SERVING_ATTENTION`` / inherit the model's). With
        ``"flash"`` the decode twin reads the block-table-gathered K/V
        through the flash kernel's segment ids — positions past the
        cache index (trash-block rows included) mask out and skip
        compute — while the step stays one fixed-shape program (the
        no-retrace join contract is unchanged).

    The engine registers itself as the module's active engine
    (:func:`get_engine`) so the live export plane's ``/status`` board
    and ``telemetry.shutdown()`` can find it.
    """

    def __init__(
        self,
        model,
        params,
        *,
        slots: int | None = None,
        block_size: int | None = None,
        num_blocks: int | None = None,
        max_queue: int | None = None,
        max_len: int | None = None,
        continuous: bool = True,
        slo_ttft_s: float | None = None,
        slo_token_s: float | None = None,
        registry: MetricsRegistry | None = None,
        clock: Callable[[], float] = time.perf_counter,
        flush_every: int = 16,
        check_memory: bool = True,
        attention: str | None = None,
    ):
        import jax.numpy as jnp

        from ..models.generate import _decode_twin, cache_template

        cfg = _config or ServingConfig()
        # attention="flash"|"naive"|"auto" overrides the model's own
        # kernel-plane switch for BOTH serving hot paths (bucketed
        # prefill and the vmapped paged decode): the decode twin's flash
        # kernel reads the block-table-gathered K/V through segment ids
        # recovered from flax's cache-index mask, so trash-block/alias
        # positions are masked (and their fully-masked tiles skipped)
        # with no extra plumbing, and the step stays one fixed-shape
        # program — mid-flight joins still retrace nothing. None (the
        # default) inherits whatever the model was built with.
        mode = attention if attention is not None else (
            cfg.attention if cfg.attention is not None
            else os.environ.get(_ENV_ATTENTION) or None
        )
        if mode is not None:
            if mode not in ("naive", "flash", "auto"):
                raise ValueError(
                    f"attention must be 'naive', 'flash', or 'auto'; "
                    f"got {mode!r}"
                )
            try:
                model = model.clone(attention=mode)
            except TypeError:
                raise ValueError(
                    f"attention={mode!r} requires a model with the "
                    f"attention switch (TransformerLM-style); "
                    f"{type(model).__name__} has no such field"
                ) from None
        self.attention = mode
        self.model = model
        self.params = params
        self.slots = _resolve(slots, cfg.slots, _ENV_SLOTS, _DEFAULT_SLOTS)
        self.block_size = _resolve(
            block_size, cfg.block_size, _ENV_BLOCK_SIZE, _DEFAULT_BLOCK_SIZE
        )
        self.max_queue = _resolve(
            max_queue, cfg.max_queue, _ENV_QUEUE, _DEFAULT_MAX_QUEUE
        )
        if self.slots < 1:
            raise ValueError(f"slots must be >= 1, got {self.slots}")
        if self.block_size < 1:
            raise ValueError(
                f"block_size must be >= 1, got {self.block_size}"
            )
        cap = int(max_len) if max_len is not None else int(model.max_len)
        cap = min(cap, int(model.max_len))
        self.max_len = (cap // self.block_size) * self.block_size
        if self.max_len < self.block_size:
            raise ValueError(
                f"max_len {cap} is below one block ({self.block_size})"
            )
        self.max_blocks_per_seq = self.max_len // self.block_size
        default_blocks = 1 + self.slots * self.max_blocks_per_seq
        nb = _resolve(num_blocks, cfg.num_blocks, _ENV_BLOCKS, default_blocks)
        self.continuous = bool(continuous)
        self.slo_ttft_s = slo_ttft_s
        self.slo_token_s = slo_token_s
        self.flush_every = max(1, int(flush_every))
        self._registry = registry
        self._clock = clock

        if not getattr(model, "batched_prefill_safe", False):
            warnings.warn(
                "model does not declare batched_prefill_safe: the "
                "engine's batched prefill can drop over-capacity prompt "
                "tokens (MoE capacity routing), so continuations may "
                "differ from generate()'s scan path — prefer ample "
                "expert capacity when serving such checkpoints",
                stacklevel=2,
            )
        self._twin = _decode_twin(model)
        head_dim = int(model.d_model) // int(model.num_heads)
        # The cache template fixes the decode-time dtype and tree shape
        # (one slot, full table width) — the decode step rebuilds the
        # flax cache from the pool through it every dispatch.
        self._tmpl = cache_template(self._twin, 1, self.max_len)
        dtype = None
        for path, leaf in self._flat_tmpl():
            if path[-1].key == "cached_key":
                dtype = leaf.dtype
                break
        self.cache = BlockKVCache(
            num_layers=int(model.num_layers),
            num_heads=int(model.num_heads),
            head_dim=head_dim,
            num_blocks=nb,
            block_size=self.block_size,
            max_blocks_per_seq=self.max_blocks_per_seq,
            dtype=dtype if dtype is not None else jnp.float32,
        )
        if check_memory:
            fits, detail = self.cache.fits_device()
            if not fits:
                raise RuntimeError(
                    f"KV pool would exhaust device memory ({detail}); "
                    f"shrink num_blocks/slots or block_size"
                )

        self._queue: deque[ServingRequest] = deque()
        self._lock = threading.Lock()
        self._slots: list[_Slot | None] = [None] * self.slots
        self._draining = False
        self._closed = False
        self._preempted = False
        self._stop = False
        self._thread: threading.Thread | None = None
        self._wake = threading.Event()
        # The serve thread's terminal exception, if it died (consumers
        # see their requests rejected with reason="error").
        self.serve_error: BaseException | None = None

        self._completed = 0
        self._rejected = 0
        self._drained = 0
        self._decode_steps = 0
        self._tokens = 0
        self._slo_violations = 0
        # Registry-counter delta baselines (see _resolve_run).
        self._counted_steps = 0
        self._counted_tokens = 0
        self._counted_records = 0

        self._decode_step = self._build_decode_step()
        self._prefill_steps: dict[int, Any] = {}
        mon = self._compile_monitor()
        if mon is not None:
            mon.track("serving.decode_step", self._decode_step)
        self._resolve_run()
        set_engine(self)

    # -- small helpers -------------------------------------------------

    def _flat_tmpl(self):
        import jax

        return jax.tree_util.tree_flatten_with_path(self._tmpl)[0]

    @staticmethod
    def _compile_monitor():
        from ..telemetry.compileplane import get_compile_monitor

        return get_compile_monitor()

    def _bucket(self, plen: int) -> int:
        """Prompt lengths round up to a block multiple so prefill
        compiles a handful of bucket shapes, not one per length."""
        return blocks_for_tokens(plen, self.block_size) * self.block_size

    # -- compiled steps ------------------------------------------------

    def _build_decode_step(self):
        """ONE fixed-shape program advancing every slot a token: gather
        each slot's pool blocks into the contiguous flax cache layout,
        run the decode twin per slot (vmapped — per-slot cache index),
        scatter the written position back, argmax the next tokens."""
        import jax
        import jax.numpy as jnp

        from ..models.generate import layer_index

        twin = self._twin
        tmpl = self._tmpl
        bs = self.block_size
        nslots = self.slots
        t_total = self.max_len

        def one(params_tree, tok, pos, k_sl, v_sl):
            # k_sl/v_sl: [layers, t_total, heads, head_dim] — this
            # slot's gathered cache; pos is ITS cache index.
            def fill(path, leaf):
                name = path[-1].key
                if name == "cached_key":
                    return k_sl[layer_index(path)][None]
                if name == "cached_value":
                    return v_sl[layer_index(path)][None]
                if name == "cache_index":
                    return pos.astype(leaf.dtype)
                return jnp.zeros(leaf.shape, leaf.dtype)

            cache = jax.tree_util.tree_map_with_path(fill, tmpl)
            logits, mut = twin.apply(
                {"params": params_tree, "cache": cache},
                tok[None, None], train=False, pos_offset=pos,
                mutable=["cache"],
            )
            knew, vnew = [], []
            for path, leaf in jax.tree_util.tree_flatten_with_path(
                mut["cache"]
            )[0]:
                name = path[-1].key
                if name not in ("cached_key", "cached_value"):
                    continue
                written = jax.lax.dynamic_slice_in_dim(
                    leaf[0], pos, 1, axis=0
                )[0]  # [heads, head_dim]
                (knew if name == "cached_key" else vnew).append(
                    (layer_index(path), written)
                )
            knew = jnp.stack([w for _, w in sorted(knew, key=lambda t: t[0])])
            vnew = jnp.stack([w for _, w in sorted(vnew, key=lambda t: t[0])])
            nxt = jnp.argmax(logits[0, -1], axis=-1).astype(jnp.int32)
            return nxt, knew, vnew

        def step(params, k_pool, v_pool, tables, positions, tokens):
            # tables: [slots, max_blocks]; positions/tokens: [slots].
            k_g = jnp.moveaxis(k_pool[:, tables], 1, 0).reshape(
                nslots, -1, t_total, k_pool.shape[3], k_pool.shape[4]
            )
            v_g = jnp.moveaxis(v_pool[:, tables], 1, 0).reshape(
                nslots, -1, t_total, v_pool.shape[3], v_pool.shape[4]
            )
            nxt, knew, vnew = jax.vmap(
                one, in_axes=(None, 0, 0, 0, 0)
            )(params["params"], tokens, positions, k_g, v_g)
            blk = jnp.take_along_axis(
                tables, (positions // bs)[:, None], axis=1
            )[:, 0]
            off = positions % bs
            # Idle slots carry all-trash tables, so their writes land in
            # block 0 — no masking, no shape change.
            k_pool = k_pool.at[:, blk, off].set(jnp.moveaxis(knew, 0, 1))
            v_pool = v_pool.at[:, blk, off].set(jnp.moveaxis(vnew, 0, 1))
            return nxt, k_pool, v_pool

        return jax.jit(step, donate_argnums=(1, 2))

    def _prefill_step(self, bucket: int):
        """The per-bucket prefill program: one causal forward over the
        padded prompt, K/V scattered straight into the pool blocks
        (masked positions land in the trash block), first generated
        token argmax'd from the last real position's logits."""
        fn = self._prefill_steps.get(bucket)
        if fn is not None:
            return fn
        import jax
        import jax.numpy as jnp

        from ..models.generate import prefill_kv

        model = self.model
        bs = self.block_size

        def prefill(params, k_pool, v_pool, tokens, length, table):
            # tokens: [bucket]; length: true prompt length; table: [MB].
            k, v, logits = prefill_kv(model, params, tokens[None])
            k = k[:, 0]  # [layers, bucket, heads, head_dim]
            v = v[:, 0]
            pos = jnp.arange(tokens.shape[0])
            blk = jnp.where(
                pos < length, table[pos // bs], jnp.int32(TRASH_BLOCK)
            )
            off = pos % bs
            k_pool = k_pool.at[:, blk, off].set(k.astype(k_pool.dtype))
            v_pool = v_pool.at[:, blk, off].set(v.astype(v_pool.dtype))
            last = jax.lax.dynamic_index_in_dim(
                logits[0], length - 1, axis=0, keepdims=False
            )
            first = jnp.argmax(last, axis=-1).astype(jnp.int32)
            return first, k_pool, v_pool

        fn = jax.jit(prefill, donate_argnums=(1, 2))
        self._prefill_steps[bucket] = fn
        mon = self._compile_monitor()
        if mon is not None:
            mon.track(f"serving.prefill_{bucket}", fn)
        return fn

    def warmup(self, prompt_lengths: tuple[int, ...] = ()) -> None:
        """Compile the decode step and the prefill buckets covering
        ``prompt_lengths`` before traffic arrives. All warmup writes
        target the trash block, so the pool and allocator are untouched
        — but the dispatches DONATE the pool buffers, so warmup must
        not race the serve thread (same single-driver rule as
        :meth:`run`): call it before :meth:`start`, or :meth:`stop`
        first."""
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError(
                "engine is serving on its background thread; warmup "
                "dispatches donate the KV pools and would race it — "
                "stop() first (new prefill buckets also compile "
                "on-demand at admission)"
            )
        import jax.numpy as jnp

        buckets = {self._bucket(max(1, int(p))) for p in prompt_lengths}
        buckets.add(self.block_size)
        mb = self.max_blocks_per_seq
        trash_table = jnp.zeros((mb,), jnp.int32)
        for bucket in sorted(buckets):
            fn = self._prefill_step(bucket)
            _, self.cache.k_pool, self.cache.v_pool = fn(
                self.params, self.cache.k_pool, self.cache.v_pool,
                jnp.zeros((bucket,), jnp.int32), jnp.int32(1), trash_table,
            )
        nxt, self.cache.k_pool, self.cache.v_pool = self._decode_step(
            self.params, self.cache.k_pool, self.cache.v_pool,
            jnp.zeros((self.slots, mb), jnp.int32),
            jnp.zeros((self.slots,), jnp.int32),
            jnp.zeros((self.slots,), jnp.int32),
        )
        np.asarray(nxt)  # block until the compile settles

    # -- admission -----------------------------------------------------

    def submit(
        self,
        prompt,
        max_new_tokens: int,
        *,
        eos_token: int | None = None,
        on_token: Callable[[int], None] | None = None,
    ) -> ServingRequest:
        """Queue a generation request; returns its handle immediately.

        Admission control is token-budget based: a request whose
        worst-case KV footprint can NEVER fit the pool raises
        ``ValueError`` (a sizing error, not load); a full queue or a
        draining engine **rejects** — the returned handle is already
        finished with ``status == "rejected"`` and the reason, and
        ``serving.admission_rejects`` counts it. Otherwise the request
        waits for a free batch slot + free blocks and joins the decode
        batch between iterations.
        """
        from .. import faults

        if faults.ARMED:
            faults.check("serving.admit")
        req = ServingRequest(
            prompt, max_new_tokens, eos_token=eos_token,
            on_token=on_token, clock=self._clock,
        )
        plen = int(req.prompt.shape[0])
        if plen < 1:
            raise ValueError("prompt must hold at least one token")
        if req.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {req.max_new_tokens}"
            )
        total = plen + req.max_new_tokens
        if total > self.max_len:
            raise ValueError(
                f"prompt + max_new_tokens = {total} exceeds the engine's "
                f"max_len {self.max_len}"
            )
        if req.eos_token is not None and not (
            0 <= int(req.eos_token) < int(self.model.vocab_size)
        ):
            raise ValueError(
                f"eos_token {req.eos_token} outside the vocabulary "
                f"[0, {self.model.vocab_size})"
            )
        if self.cache.blocks_for(total) > self.cache.num_blocks - 1:
            raise ValueError(
                f"request needs {self.cache.blocks_for(total)} blocks but "
                f"the pool only holds {self.cache.num_blocks - 1}"
            )
        with self._lock:
            # _stop (a merely-parked engine between stop() and the next
            # run()/start()) does NOT reject: submissions queue and the
            # next driver serves them. Only a drain or teardown sheds.
            if self._draining or self._closed:
                self._reject(
                    req, "draining" if self._draining else "shutdown"
                )
                return req
            if len(self._queue) >= self.max_queue:
                self._reject(req, "queue_full")
                return req
            self._queue.append(req)
        self._wake.set()
        return req

    def _reject(
        self, req: ServingRequest, reason: str, *, kv_blocks: int = 0
    ) -> None:
        self._rejected += 1
        req._finish(REJECTED, reason)
        reg = self._live_registry()
        if getattr(reg, "enabled", True):
            reg.counter("serving.admission_rejects", reason=reason).inc()
        # Live lookup (like the registry above, not the per-run
        # resolution): rejects can happen from submit() before any
        # run()/start() resolved the plane, and every rejected request
        # must still land in the log — the drain-completeness contract.
        obs = _observe_mod.get_request_observer()
        if obs is not None and obs.enabled:
            obs.observe_terminal(req, kv_blocks=kv_blocks)
            if reason == "queue_full":
                # The load-shed moment is when the pool census matters:
                # fold it into the OOM-style debug bundle (rate-limited
                # to the first shed).
                obs.maybe_write_bundle(self, "queue_full")

    def _live_registry(self) -> MetricsRegistry:
        return self._registry if self._registry is not None else get_registry()

    def _admit_phase(self) -> int:
        """Move queued requests into free batch slots (continuous mode:
        between any two iterations; static mode: only once every slot
        has drained), prefilling each admission. FIFO — a head request
        waiting on blocks holds the line (documented in
        docs/serving.md)."""
        if not self.continuous and any(s is not None for s in self._slots):
            return 0
        admitted = 0
        while True:
            free_ix = next(
                (i for i, s in enumerate(self._slots) if s is None), None
            )
            if free_ix is None:
                break
            with self._lock:
                if not self._queue:
                    break
                head = self._queue[0]
                total = int(head.prompt.shape[0]) + head.max_new_tokens
                if not self.cache.can_alloc(total):
                    break
                self._queue.popleft()
            self._admit(head, free_ix, total)
            admitted += 1
        return admitted

    def _admit(self, req: ServingRequest, slot_ix: int, total: int) -> None:
        import jax.numpy as jnp

        req.admitted_t = self._clock()
        req.status = ACTIVE
        blocks = self.cache.alloc(total)
        table = self.cache.table_row(blocks)
        slot = _Slot(req, blocks, table)
        plen = int(req.prompt.shape[0])
        bucket = self._bucket(plen)
        padded = np.zeros((bucket,), np.int32)
        padded[:plen] = req.prompt
        fn = self._prefill_step(bucket)
        first, self.cache.k_pool, self.cache.v_pool = fn(
            self.params, self.cache.k_pool, self.cache.v_pool,
            jnp.asarray(padded), jnp.int32(plen), jnp.asarray(table),
        )
        slot.position = plen
        slot.generated = 1
        slot.last_token = int(first)
        self._slots[slot_ix] = slot
        req._deliver(slot.last_token)
        self._tokens += 1
        if self._record:
            reg = self._reg
            if req.queue_wait_s is not None:
                reg.histogram("serving.queue_wait_seconds").observe(
                    req.queue_wait_s
                )
        if slot.generated >= req.max_new_tokens or (
            req.eos_token is not None and slot.last_token == int(req.eos_token)
        ):
            self._evict(slot_ix)

    # -- decode --------------------------------------------------------

    def _decode_tick(self) -> None:
        """One engine iteration's decode phase: a single dispatch over
        every slot, then host-side delivery/eviction."""
        import jax.numpy as jnp

        from .. import faults

        if faults.ARMED:
            faults.check("serving.decode")
        mb = self.max_blocks_per_seq
        tables = np.zeros((self.slots, mb), np.int32)
        positions = np.zeros((self.slots,), np.int32)
        tokens = np.zeros((self.slots,), np.int32)
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            tables[i] = slot.table
            positions[i] = slot.position
            tokens[i] = slot.last_token
        nxt, self.cache.k_pool, self.cache.v_pool = self._decode_step(
            self.params, self.cache.k_pool, self.cache.v_pool,
            jnp.asarray(tables), jnp.asarray(positions), jnp.asarray(tokens),
        )
        nxt = np.asarray(nxt)
        self._decode_steps += 1
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            tok = int(nxt[i])
            slot.position += 1
            slot.generated += 1
            slot.last_token = tok
            slot.req._deliver(tok)
            self._tokens += 1
            if slot.generated >= slot.req.max_new_tokens or (
                slot.req.eos_token is not None
                and tok == int(slot.req.eos_token)
            ):
                self._evict(i)

    def _evict(self, slot_ix: int) -> None:
        """Finish a slot's request and return its blocks to the free
        list — the eviction half of the paged-cache contract."""
        slot = self._slots[slot_ix]
        assert slot is not None
        self._slots[slot_ix] = None
        self.cache.free(slot.blocks)
        req = slot.req
        req._finish(FINISHED)
        self._completed += 1
        violations = []
        if self.slo_ttft_s is not None and (
            req.ttft_s is not None and req.ttft_s > self.slo_ttft_s
        ):
            violations.append("ttft")
        if self.slo_token_s is not None and (
            req.per_token_s is not None
            and req.per_token_s > self.slo_token_s
        ):
            violations.append("per_token")
        self._slo_violations += len(violations)
        if self._record:
            reg = self._reg
            if req.ttft_s is not None:
                reg.histogram("serving.ttft_seconds").observe(req.ttft_s)
            if req.per_token_s is not None:
                reg.histogram("serving.token_seconds").observe(
                    req.per_token_s
                )
            # Request-size mix (token-count ladder, not the latency
            # ladders): completions only — a rejected request's sizes
            # live in its JSONL record, not the served-mix histograms.
            reg.histogram("serving.prompt_tokens").observe(
                int(req.prompt.shape[0])
            )
            reg.histogram("serving.output_tokens").observe(len(req.tokens))
            reg.counter("serving.requests_completed").inc()
            for kind in violations:
                reg.counter("serving.slo_violations", kind=kind).inc()
        if self._observer is not None:
            self._observer.observe_terminal(
                req, kv_blocks=len(slot.blocks),
                violations=tuple(violations),
            )

    # -- the loop ------------------------------------------------------

    @property
    def active_count(self) -> int:
        return sum(1 for s in self._slots if s is not None)

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def _begin_drain(self, *, preempted: bool) -> None:
        """Stop admitting: queued requests are rejected, active slots
        decode to completion (the SIGTERM grace-window contract —
        in-flight work finishes, nothing new starts)."""
        with self._lock:
            self._draining = True
            self._preempted = self._preempted or preempted
            dropped = list(self._queue)
            self._queue.clear()
        self._drained += self.active_count
        for req in dropped:
            self._reject(req, "preempted" if preempted else "draining")

    def _iteration(self) -> bool:
        """One scheduler iteration: preemption poll → admissions →
        decode tick → liveness/metrics. Returns whether any work
        happened."""
        from ..runtime import preemption_requested
        from ..telemetry.watchdog import notify_progress

        if preemption_requested() and not self._draining:
            self._begin_drain(preempted=True)
        admitted = self._admit_phase()
        ticked = False
        if any(s is not None for s in self._slots):
            self._decode_tick()
            ticked = True
        if admitted or ticked:
            # Progress ONLY when work happened: an idle serve thread
            # bumping the process-global watchdog counter every poll
            # would mask a co-resident train loop's stall from the
            # watchdog and /healthz (idle != progress).
            notify_progress(1)
        if admitted or (
            ticked and self._decode_steps % self.flush_every == 0
        ):
            self._observe(phase="running")
        return bool(admitted) or ticked

    def _observe(self, phase: str) -> None:
        """Refresh the gauges + the exporter status board (resolved once
        per run — never on the fully-off path)."""
        obs = self._observer
        if self._record:
            reg = self._reg
            reg.gauge("serving.queue_depth").set(self.queue_depth)
            reg.gauge("serving.active_sequences").set(self.active_count)
            reg.gauge("serving.kv_blocks_in_use").set(self.cache.used_blocks)
            reg.gauge("serving.kv_blocks_free").set(self.cache.free_blocks)
            reg.gauge("serving.kv_high_watermark_blocks").set(
                self.cache.high_watermark_blocks
            )
            reg.gauge("serving.kv_fragmentation").set(
                self.cache.fragmentation
            )
            reg.counter("serving.decode_steps").inc(
                self._decode_steps - self._counted_steps
            )
            reg.counter("serving.tokens_generated").inc(
                self._tokens - self._counted_tokens
            )
            self._counted_steps = self._decode_steps
            self._counted_tokens = self._tokens
            if obs is not None:
                for w, rate in obs.burn.burn_rates().items():
                    reg.gauge(
                        "serving.slo_burn_rate", window=f"{w:g}"
                    ).set(rate)
                reg.counter("serving.requests_logged").inc(
                    obs.records - self._counted_records
                )
                self._counted_records = obs.records
        if obs is not None:
            # Feed the anomaly plane the multi-window alert rate (both
            # windows must be burning) — the `slo_burn` rule owns the
            # threshold and the warn/halt policy.
            rate = obs.burn.alert_rate()
            if rate is not None:
                from ..telemetry.anomaly import get_anomaly_detector

                det = get_anomaly_detector()
                if det is not None and det.enabled:
                    det.observe(slo_burn=rate, step=self._decode_steps)
        if self._exporter is not None:
            total = self.cache.num_blocks - 1
            board: dict[str, Any] = dict(
                phase=phase,
                continuous=self.continuous,
                slots=self.slots,
                active=self.active_count,
                queued=self.queue_depth,
                completed=self._completed,
                rejected=self._rejected,
                drained=self._drained,
                decode_steps=self._decode_steps,
                tokens=self._tokens,
                kv_blocks_in_use=self.cache.used_blocks,
                kv_blocks_total=total,
                kv_util=(self.cache.used_blocks / total) if total else 0.0,
                kv_high_watermark=self.cache.high_watermark_blocks,
                kv_fragmentation=self.cache.fragmentation,
                slo_violations=self._slo_violations,
            )
            if obs is not None:
                board.update(obs.board())
            self._exporter.note_serving(**board)

    def _resolve_run(self) -> None:
        """The once-per-run resolution of every observability surface
        the loop touches (the PR 4 zero-cost contract: fully off, the
        per-iteration path reads two booleans)."""
        from ..telemetry.export import get_exporter

        self._reg = self._live_registry()
        self._record = bool(getattr(self._reg, "enabled", True))
        self._exporter = get_exporter()
        obs = _observe_mod.get_request_observer()
        self._observer = obs if (obs is not None and obs.enabled) else None
        # NOTE: the _counted_* delta baselines are NOT reset here — they
        # live for the engine's lifetime (set once in __init__), so
        # ticks that happened between the last _observe and a driver
        # switch still reach the cumulative registry counters at the
        # next flush instead of being silently dropped.

    def drain(self) -> None:
        """Graceful wind-down without a signal: stop admitting (queued
        requests rejected), let active slots decode to completion on the
        next :meth:`run` / serve iterations."""
        if not self._draining:
            self._begin_drain(preempted=False)

    def step(self) -> bool:
        """Run ONE scheduler iteration inline (test/tooling hook);
        returns whether any work happened."""
        return self._iteration()

    def run(self) -> dict[str, Any]:
        """Drive the engine until queue and slots drain (or a
        preemption drain completes); returns the run summary. The
        blocking, host-driven serving loop — the serving counterpart of
        ``train_loop``."""
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError(
                "engine is already serving on its background thread; "
                "stop() it before driving run() inline"
            )
        # A previous stop() parked the engine (_stop gates submit);
        # driving it inline un-parks it — stop()-then-run() is the
        # documented sequence for switching drivers.
        self._stop = False
        self._resolve_run()
        t0 = self._clock()
        tokens0 = self._tokens
        self._observe(phase="running")
        while True:
            worked = self._iteration()
            if not worked and self.active_count == 0 and (
                self.queue_depth == 0 or self._draining
            ):
                break
        return self._finish_run(t0, tokens0)

    def _finish_run(self, t0: float, tokens0: int) -> dict[str, Any]:
        wall = self._clock() - t0
        phase = "preempted" if self._preempted else "finished"
        self._observe(phase=phase)
        reg = self._reg
        if self._record and reg.sinks:
            reg.flush()
        summary = {
            "completed": self._completed,
            "rejected": self._rejected,
            "drained": self._drained,
            "preempted": self._preempted,
            "decode_steps": self._decode_steps,
            "tokens": self._tokens,
            "slo_violations": self._slo_violations,
            "wall_seconds": wall,
            # Rate = THIS run's tokens over THIS run's wall — the other
            # counters are engine-lifetime totals, and dividing a
            # lifetime count by one run's wall would inflate the rate
            # after a driver switch (background serve, then run()).
            "tokens_per_sec": (
                (self._tokens - tokens0) / wall if wall > 0 else 0.0
            ),
        }
        return summary

    # -- background serving -------------------------------------------

    def _fail_pending(self, reason: str, *, include_active: bool) -> None:
        """Reject everything still pending (error/shutdown paths),
        counted through the same :meth:`_reject` accounting as every
        other rejection; evicted slots return their blocks."""
        with self._lock:
            pending = list(self._queue)
            self._queue.clear()
        for req in pending:
            self._reject(req, reason)
        if include_active:
            for i, slot in enumerate(self._slots):
                if slot is not None:
                    self._slots[i] = None
                    self.cache.free(slot.blocks)
                    self._reject(
                        slot.req, reason, kv_blocks=len(slot.blocks)
                    )

    def start(self) -> "InferenceEngine":
        """Serve on a background thread until :meth:`stop`: the loop
        sleeps on an event when idle and wakes on :meth:`submit` — the
        streaming-consumer spelling (``req.stream()`` on the caller's
        thread)."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop = False
        self.serve_error = None
        self._resolve_run()

        def serve() -> None:
            while not self._stop:
                try:
                    worked = self._iteration()
                except BaseException as exc:
                    # A dying serve thread must not strand consumers
                    # blocked in wait()/stream(): bank the error, fail
                    # every pending request (reason="error" — their
                    # handles unblock and report it), and exit.
                    self.serve_error = exc
                    warnings.warn(
                        f"serving loop failed: {exc!r}; pending requests "
                        f"rejected (reason='error')",
                        stacklevel=2,
                    )
                    self._fail_pending("error", include_active=True)
                    return
                if not worked and self.active_count == 0:
                    self._wake.wait(timeout=0.05)
                    self._wake.clear()

        self._thread = threading.Thread(
            target=serve, name="fluxmpi-serving", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout: float = 30.0) -> bool:
        """Stop the background serving thread (idempotent); returns
        whether it fully stopped. Queued and active requests are NOT
        completed — use a preemption drain (``request_preemption()``)
        for a graceful wind-down. A thread that outlives ``timeout``
        (wedged in a dispatch or a chaos ``delay=`` stall) keeps its
        reference — a later :meth:`stop`/:meth:`close` retries — so
        teardown never frees state a live thread still touches."""
        self._stop = True
        self._wake.set()
        thread = self._thread
        if thread is None:
            return True
        thread.join(timeout=timeout)
        if thread.is_alive():
            warnings.warn(
                f"serving thread still running after {timeout}s "
                f"(wedged dispatch?); its state is left untouched",
                stacklevel=2,
            )
            return False
        self._thread = None
        return True

    def close(self) -> None:
        """Full teardown: stop the serve thread, fail anything still
        pending, release every block, drop the device pools, and
        deregister. ``telemetry.shutdown()``'s reset path. If the serve
        thread cannot be joined, active slots and the pools are left in
        place (leak over corruption — a resuming thread must never
        double-free blocks or decode into re-zeroed pools)."""
        self._closed = True  # submits from here on reject ("shutdown")
        stopped = self.stop()
        self._fail_pending("shutdown", include_active=stopped)
        if stopped:
            self.cache.drop_pools()
        if get_engine() is self:
            set_engine(None)
