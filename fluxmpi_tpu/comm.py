"""Eager collective communication over the device mesh.

TPU-native redesign of the reference's "MPIExtensions" layer
(reference: src/mpi_extensions.jl). The reference exposes eager, host-driven
collectives — blocking ``allreduce!/bcast!/reduce!`` delegating to libmpi
(src/mpi_extensions.jl:97-155) and hand-``ccall``ed non-blocking
``Iallreduce!/Ibcast!`` (src/mpi_extensions.jl:26-88) — with a CPU-staging
fallback for CUDA-unaware MPI.

Here the transport is XLA collectives over ICI, compiled with ``shard_map``
over the global mesh. The *semantic model* is preserved exactly: a "per-worker
value" is a ``jax.Array`` whose leading axis indexes the workers (one slice
per device, sharded over the data-parallel mesh axis); ``allreduce`` leaves
every worker holding the reduction, ``bcast`` leaves every worker holding the
root's slice, ``reduce`` updates only the root's slice. The
blocking-vs-non-blocking split of the reference collapses into XLA's async
dispatch: every collective here returns immediately with a future-backed
array (the analogue of ``Iallreduce!``'s request), and blocking on the result
is ``.block_until_ready()`` (the analogue of ``MPI.Waitall!``,
src/optimizer.jl:59). ``iallreduce``/``ibcast`` are provided as explicit
spellings of that for API parity.

The CUDA-aware/staging dichotomy disappears on ICI; a host-staging debug path
survives behind ``config.disable_device_collectives()`` (the analogue of the
reference's CPU-staging fallback, src/mpi_extensions.jl:97-106).
"""

from __future__ import annotations

import functools
import time
import warnings
from typing import Any, Callable

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import config
from . import faults as _faults
from .runtime import global_mesh
from .telemetry import get_registry as _telemetry_registry
from .telemetry import tracing as _tracing
from .telemetry.flight_recorder import (
    get_flight_recorder as _flight_recorder,
)

__all__ = [
    "cpu",
    "device",
    "allreduce",
    "bcast",
    "reduce",
    "iallreduce",
    "ibcast",
    "barrier",
    "shard_ranks",
    "unshard_ranks",
    "host_allreduce",
    "host_allgather",
    "host_bcast",
    "Request",
]

# ---------------------------------------------------------------------------
# Device transfer helpers (reference: src/mpi_extensions.jl:5-8 — minimal
# cpu/gpu adaptors, identity on non-arrays).
# ---------------------------------------------------------------------------


def cpu(x: Any) -> Any:
    """Move an array to host memory; identity on non-arrays
    (reference ``cpu``, src/mpi_extensions.jl:7)."""
    if isinstance(x, (jax.Array, np.ndarray)):
        return np.asarray(jax.device_get(x))
    return x


def device(x: Any, d: jax.Device | jax.sharding.Sharding | None = None) -> Any:
    """Move an array to device; identity on non-arrays
    (reference ``gpu``, src/mpi_extensions.jl:8 — spelled ``device`` here
    because the target is a TPU chip or a sharding, not a CUDA context)."""
    if isinstance(x, (jax.Array, np.ndarray)):
        return jax.device_put(x, d)
    return x


# ---------------------------------------------------------------------------
# Reduction ops
# ---------------------------------------------------------------------------

_OP_ALIASES = {
    "+": "sum",
    "sum": "sum",
    "add": "sum",
    "*": "prod",
    "prod": "prod",
    "mul": "prod",
    "min": "min",
    "max": "max",
    "mean": "mean",
    "avg": "mean",
}


def _canonical_op(op: str) -> str:
    try:
        return _OP_ALIASES[op]
    except (KeyError, TypeError):
        raise ValueError(
            f"unsupported reduction op {op!r}; expected one of "
            f"{sorted(set(_OP_ALIASES))}"
        ) from None


def _tree_reduce_stacked(op: str, stacked: jnp.ndarray, axis: int = 0):
    if op == "sum":
        return jnp.sum(stacked, axis=axis)
    if op == "prod":
        return jnp.prod(stacked, axis=axis)
    if op == "min":
        return jnp.min(stacked, axis=axis)
    if op == "max":
        return jnp.max(stacked, axis=axis)
    if op == "mean":
        return jnp.mean(stacked, axis=axis)
    raise AssertionError(op)


# ---------------------------------------------------------------------------
# Per-worker value plumbing
# ---------------------------------------------------------------------------


def _axis_and_size(mesh: Mesh, axis_name: str | None) -> tuple[str, int]:
    if axis_name is not None:
        # Explicit names must exist — silently reducing over a different
        # axis on a typo would produce wrong sums with no error.
        if axis_name not in mesh.shape:
            raise ValueError(
                f"axis {axis_name!r} not in mesh axes {mesh.axis_names}"
            )
        return axis_name, mesh.shape[axis_name]
    name = config.DP_AXIS_NAME if config.DP_AXIS_NAME in mesh.shape else mesh.axis_names[0]
    return name, mesh.shape[name]


@functools.lru_cache(maxsize=None)
def _ranks_sharding(mesh: Mesh, name: str, ndim: int) -> NamedSharding:
    # One NamedSharding per (mesh, axis, rank-count) — constructing a fresh
    # one per call was measurable per-batch/per-collective overhead.
    return NamedSharding(mesh, P(name, *([None] * (ndim - 1))))


def shard_ranks(
    x: Any, mesh: Mesh | None = None, axis_name: str | None = None
) -> jax.Array:
    """Lay a stacked per-worker value ``x`` (leading axis = world size) out
    across the mesh, one slice per worker. An input already carrying the
    target layout is returned as-is (no restaging device_put)."""
    mesh = mesh or global_mesh()
    name, size = _axis_and_size(mesh, axis_name)
    x = jnp.asarray(x)
    if x.ndim == 0 or x.shape[0] != size:
        raise ValueError(
            f"per-worker value must have leading axis == world size {size}, "
            f"got shape {x.shape}"
        )
    sharding = _ranks_sharding(mesh, name, x.ndim)
    if isinstance(x, jax.Array) and x.sharding.is_equivalent_to(
        sharding, x.ndim
    ):
        return x
    return jax.device_put(x, sharding)


def unshard_ranks(x: jax.Array) -> np.ndarray:
    """Gather a per-worker value back to a host numpy array."""
    return np.asarray(jax.device_get(x))


@functools.lru_cache(maxsize=None)
def _collective_fn(
    mesh: Mesh, axis: str, kind: str, op: str, root: int, donate: bool
) -> Callable[[jax.Array], jax.Array]:
    spec = P(axis)

    from ._collective_ops import allreduce_by_op, masked_psum_bcast

    def body(x):  # x: [1, ...] — this worker's slice
        if kind == "allreduce":
            return allreduce_by_op(x, op, axis)
        if kind == "bcast":
            # ONE O(bytes) AllReduce instead of the O(world × bytes)
            # all-gather+slice this used to be (VERDICT r1 weak #3).
            return masked_psum_bcast(x, root, axis)
        if kind == "reduce":
            # O(bytes): the reduction rides the same AllReduce as allreduce;
            # the root-only visibility is a local select.
            red = allreduce_by_op(x, op, axis)
            idx = jax.lax.axis_index(axis)
            return jnp.where(idx == root, red, x)
        raise AssertionError(kind)

    # Lazy import: the compat seam lives under fluxmpi_tpu.parallel, whose
    # package init must not run while fluxmpi_tpu's own init is mid-import.
    from .parallel._compat import shard_map

    fn = shard_map(body, mesh=mesh, in_specs=(spec,), out_specs=spec)
    # Donation lets XLA write the reduction into the input buffer — the
    # zero-copy analogue of the reference's in-place ``allreduce!``
    # (src/mpi_extensions.jl:97-111). Input and output share one sharding,
    # so the aliasing is always representable.
    return jax.jit(fn, donate_argnums=(0,) if donate else ())


def _host_collective(
    x: Any, kind: str, op: str, root: int, mesh: Mesh, axis_name: str
) -> jax.Array:
    """Host-staging fallback (debug path; analogue of the reference's
    CPU-staging for CUDA-unaware MPI, src/mpi_extensions.jl:97-106)."""
    h = np.asarray(jax.device_get(x))
    if kind == "allreduce":
        red = np.asarray(_tree_reduce_stacked(op, jnp.asarray(h), axis=0))
        out = np.broadcast_to(red[None], h.shape).copy()
    elif kind == "bcast":
        out = np.broadcast_to(h[root][None], h.shape).copy()
    else:  # reduce
        out = h.copy()
        out[root] = np.asarray(_tree_reduce_stacked(op, jnp.asarray(h), axis=0))
    return shard_ranks(out, mesh, axis_name)


# ---------------------------------------------------------------------------
# Instrumentation: every eager collective records per-op call count, payload
# bytes, and host-blocking seconds into the default telemetry registry.
# "Host-blocking" is the wall time the *host* spends inside the call — for
# the device path that is staging + async dispatch (the device work itself
# overlaps; block on the result to time it), for the host-staging path it
# includes the full device_get/reduce/device_put round trip. Cost when no
# sink is attached: three dict hits and a few float ops per call.
#
# The flight recorder is the second consumer: _begin_op appends a ring
# entry (monotonic sequence number, op, path, nbytes) BEFORE the
# potentially-blocking call, _record_op marks it completed after — so a
# rank hung inside a collective leaves a tail entry with completed=False
# naming exactly which collective it is stuck in, and diffing per-host
# dumps localizes a desync (see telemetry/flight_recorder.py). When
# tracing is enabled the same t0/t1 pair lands on the span timeline as a
# comm.<op> event. Both are one deque append — no locks on this path.
#
# Zero-cost-when-off: one `_instrumentation_on()` check (three attribute
# reads) gates ALL of the above. With the registry, the flight recorder,
# and the tracer disabled, a collective performs no perf_counter reads, no
# labeled-handle lookups, and no flight/trace appends. When on, the three
# labeled handles per (op, path) are resolved once and cached — the
# steady-state cost is attribute reads + float ops, not three registry
# dict lookups per call (they key by sorted label tuples, which allocates).
# ---------------------------------------------------------------------------

# (op, path) -> (registry, registry.version, calls, bytes, block_seconds).
# Invalidated by identity/version mismatch: set_registry() swaps the
# registry object, reset() bumps the version (orphaning the instruments).
_handles: dict[tuple[str, str], tuple[Any, int, Any, Any, Any]] = {}


def _instrumentation_on() -> bool:
    """The single fast-guard for the collective hot path."""
    return (
        _telemetry_registry().enabled
        or _flight_recorder().enabled
        or _tracing.get_tracer().enabled
    )


def _begin_op(op_name: str, path: str, nbytes: int) -> Any:
    try:
        return _flight_recorder().begin(op_name, path, nbytes)
    except Exception:  # instrumentation must never take down a collective
        return None


def _abort_op(flight: Any) -> None:
    """Finalize a flight entry whose collective RAISED: an exception is
    not a hang, and a permanently-incomplete entry would make every
    later dump name a long-dead error as the in-flight collective."""
    if flight is None:
        return
    try:
        _flight_recorder().abort(flight)
    except Exception:
        pass


def _record_op(
    op_name: str, path: str, nbytes: int, t0: float, flight: Any = None
) -> None:
    try:
        t1 = time.perf_counter()
        if flight is not None:
            _flight_recorder().complete(flight)
        _tracing.add_complete_event(
            "comm." + op_name, t0, t1, path=path, nbytes=int(nbytes)
        )
        reg = _telemetry_registry()
        if not reg.enabled:
            return
        key = (op_name, path)
        cached = _handles.get(key)
        if (
            cached is None
            or cached[0] is not reg
            or cached[1] != reg.version
        ):
            cached = (
                reg,
                reg.version,
                reg.counter("comm.calls", op=op_name, path=path),
                reg.counter("comm.bytes", op=op_name, path=path),
                reg.histogram("comm.block_seconds", op=op_name, path=path),
            )
            _handles[key] = cached
        _, _, calls, nbytes_total, block = cached
        calls.inc()
        nbytes_total.inc(float(nbytes))
        block.observe(t1 - t0)
    except Exception:  # instrumentation must never take down a collective
        pass


def _run_collective(
    x: Any,
    kind: str,
    op: str = "sum",
    root: int = 0,
    mesh: Mesh | None = None,
    axis_name: str | None = None,
    donate: bool = False,
) -> jax.Array:
    # Chaos hook first (one attribute read when disarmed — the same
    # zero-cost-when-off contract as the instrumentation guard below):
    # an injected collective failure fires before any staging, like a
    # transport error would.
    if _faults.ARMED:
        _faults.check("comm." + kind)
    # One cheap guard up front: the fully-off path (no telemetry, no
    # flight recorder, no tracing) must do no timing and no dict work.
    instrumented = _instrumentation_on()
    t0 = time.perf_counter() if instrumented else 0.0
    mesh = mesh or global_mesh()
    name, size = _axis_and_size(mesh, axis_name)
    if not 0 <= root < size:
        raise ValueError(f"root rank {root} out of range for world size {size}")
    if config.DEVICE_COLLECTIVES_DISABLED:
        if donate:
            # The host-staging debug path round-trips through numpy; there
            # is no buffer to reuse. Same silent-degradation signal as the
            # reshard case below.
            warnings.warn(
                "donate=True has no effect with device collectives "
                "disabled: the host-staging path copies through numpy "
                "(no in-place reuse)",
                stacklevel=3,
            )
        xs = jnp.asarray(x)
        if xs.ndim == 0 or xs.shape[0] != size:
            raise ValueError(
                f"per-worker value must have leading axis == world size "
                f"{size}, got shape {xs.shape}"
            )
        if not instrumented:
            return _host_collective(xs, kind, op, root, mesh, name)
        flight = _begin_op(kind, "host", xs.nbytes)
        try:
            out = _host_collective(xs, kind, op, root, mesh, name)
        except BaseException:
            _abort_op(flight)
            raise
        _record_op(kind, "host", xs.nbytes, t0, flight)
        return out
    xs = shard_ranks(x, mesh, name)
    # Host (non-jax.Array) inputs are staged into a buffer that is provably
    # ours alone — donate it so the collective writes in place instead of
    # allocating a second output buffer. Device-array inputs are only
    # consumed on explicit ``donate=True`` (the reference's mutating
    # ``allreduce!`` contract): device_put can return a NEW Array object
    # that still aliases the caller's buffers (e.g. a layout-identical but
    # non-``==`` sharding spec), so object identity of the staged array
    # cannot prove a private copy.
    fresh = not isinstance(x, jax.Array)
    if donate and not fresh and not x.sharding.is_equivalent_to(
        xs.sharding, x.ndim
    ):
        # The staging device_put materialized a reshard; donating that copy
        # frees nothing the caller owns, so the promised in-place behavior
        # silently degrades — say so instead.
        warnings.warn(
            "donate=True on a device array that required resharding: the "
            "staged copy is donated but the caller's buffer stays live "
            "(no in-place reuse). Pre-shard with shard_ranks() to get "
            "zero-copy collectives.",
            stacklevel=3,
        )
    fn = _collective_fn(mesh, name, kind, op, root, donate or fresh)
    if not instrumented:
        return fn(xs)
    nbytes = xs.nbytes
    flight = _begin_op(kind, "device", nbytes)
    try:
        out = fn(xs)
    except BaseException:
        _abort_op(flight)
        raise
    _record_op(kind, "device", nbytes, t0, flight)
    return out


# ---------------------------------------------------------------------------
# Public eager collectives (reference: src/mpi_extensions.jl:26-155)
# ---------------------------------------------------------------------------


def allreduce(
    x: Any,
    op: str = "sum",
    *,
    mesh: Mesh | None = None,
    axis_name: str | None = None,
    donate: bool = False,
) -> jax.Array:
    """All-reduce a per-worker value: every worker's slice becomes the
    reduction of all workers' slices.

    Analogue of ``allreduce!`` (reference: src/mpi_extensions.jl:97-111),
    lowered to an XLA AllReduce over ICI instead of ``MPI.Allreduce!``.
    ``x`` has leading axis == world size (one slice per worker).

    ``donate=True`` reproduces the reference's in-place contract: the input
    buffer is handed to XLA for reuse as the output (zero extra copies) and
    ``x`` must not be used afterwards. Host (numpy) inputs are staged into a
    private buffer that is always donated; device-array inputs — even ones
    that need resharding — are never consumed without the flag, because a
    staging ``device_put`` may alias the caller's buffers. With
    ``donate=True``, in-place reuse of the *caller's* buffer only happens
    when ``x`` is already laid out as :func:`shard_ranks` would place it;
    a reshard-staged input donates only the staging copy (warned).
    """
    return _run_collective(
        x, "allreduce", _canonical_op(op), 0, mesh, axis_name, donate
    )


def bcast(
    x: Any,
    root: int = 0,
    *,
    mesh: Mesh | None = None,
    axis_name: str | None = None,
    donate: bool = False,
) -> jax.Array:
    """Broadcast the root worker's slice to all workers.

    Analogue of ``bcast!`` (reference: src/mpi_extensions.jl:119-133), lowered
    to XLA all-gather + slice (collective-broadcast) instead of ``MPI.Bcast!``.
    ``donate=True`` consumes an already-sharded input in place (see
    :func:`allreduce`).
    """
    return _run_collective(x, "bcast", "sum", root, mesh, axis_name, donate)


def reduce(
    x: Any,
    op: str = "sum",
    root: int = 0,
    *,
    mesh: Mesh | None = None,
    axis_name: str | None = None,
    donate: bool = False,
) -> jax.Array:
    """Reduce to the root worker: root's slice becomes the reduction, other
    workers keep their input slice.

    Analogue of ``reduce!`` (reference: src/mpi_extensions.jl:141-155). On ICI
    an all-reduce is as cheap as a rooted reduce, so this lowers to
    all-gather + local reduce masked to the root. ``donate=True`` consumes an
    already-sharded input in place (see :func:`allreduce`).
    """
    return _run_collective(
        x, "reduce", _canonical_op(op), root, mesh, axis_name, donate
    )


class Request:
    """Completion handle for the non-blocking spellings.

    The analogue of ``MPI.Request`` returned by the reference's hand-bound
    ``Iallreduce!``/``Ibcast!`` (src/mpi_extensions.jl:26-88). On TPU every
    collective is async-dispatched by the XLA runtime; ``wait()`` is the
    analogue of ``MPI.Wait!``/``Waitall!`` (src/optimizer.jl:59).
    """

    def __init__(self, value: jax.Array) -> None:
        self._value = value

    def wait(self) -> jax.Array:
        self._value.block_until_ready()
        return self._value

    @staticmethod
    def wait_all(requests: "list[Request]") -> list[jax.Array]:
        return [r.wait() for r in requests]


def iallreduce(
    x: Any,
    op: str = "sum",
    *,
    mesh: Mesh | None = None,
    axis_name: str | None = None,
) -> tuple[jax.Array, Request]:
    """Non-blocking all-reduce: returns ``(value, request)`` immediately;
    the value materializes asynchronously (reference ``Iallreduce!``,
    src/mpi_extensions.jl:26-60)."""
    out = allreduce(x, op, mesh=mesh, axis_name=axis_name)
    return out, Request(out)


def ibcast(
    x: Any,
    root: int = 0,
    *,
    mesh: Mesh | None = None,
    axis_name: str | None = None,
) -> tuple[jax.Array, Request]:
    """Non-blocking broadcast (reference ``Ibcast!``,
    src/mpi_extensions.jl:70-88)."""
    out = bcast(x, root, mesh=mesh, axis_name=axis_name)
    return out, Request(out)


def barrier(tag: str = "fluxmpi_barrier") -> None:
    """Block until all processes reach this point.

    Analogue of ``MPI.Barrier`` (reference: src/common.jl:91). Multi-host:
    a global device sync; single-process: drain local async dispatch.
    """
    def _sync() -> None:
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(tag)
        else:
            jax.effects_barrier()

    if _faults.ARMED:
        _faults.check("comm.barrier")
    if not _instrumentation_on():
        _sync()
        return
    t0 = time.perf_counter()
    flight = _begin_op("barrier", "host", 0)
    try:
        _sync()
    except BaseException:
        _abort_op(flight)
        raise
    _record_op("barrier", "host", 0, t0, flight)


# ---------------------------------------------------------------------------
# Host-level cross-process collectives (multi-host SPMD): operate on values
# that live per controller process, the closest analogue of the reference's
# per-rank host arrays when each process drives several chips.
# ---------------------------------------------------------------------------


def host_allreduce(x: Any, op: str = "sum") -> np.ndarray:
    """Reduce a per-process host value across all controller processes."""
    if _faults.ARMED:
        _faults.check("comm.host_allreduce")
    op = _canonical_op(op)
    t0 = time.perf_counter()
    h = np.asarray(x)
    flight = _begin_op("host_allreduce", "host", h.nbytes)
    if jax.process_count() == 1:
        _record_op("host_allreduce", "host", h.nbytes, t0, flight)
        return h
    try:  # pragma: no cover - multihost only
        from jax.experimental import multihost_utils

        gathered = multihost_utils.process_allgather(h, tiled=False)
        out = np.asarray(
            _tree_reduce_stacked(op, jnp.asarray(gathered), axis=0)
        )
    except BaseException:  # pragma: no cover - multihost only
        _abort_op(flight)
        raise
    _record_op("host_allreduce", "host", h.nbytes, t0, flight)
    return out


def host_allgather(x: Any) -> np.ndarray:
    """Gather a per-process host value from every controller process:
    returns an array with a leading ``process_count()`` axis (this
    process's value at its own index). One collective yields the whole
    per-host picture — min/max/mean/outliers are then local math, which
    is why the :class:`~fluxmpi_tpu.telemetry.TrainingMonitor` uses this
    instead of one :func:`host_allreduce` per statistic."""
    if _faults.ARMED:
        _faults.check("comm.host_allgather")
    t0 = time.perf_counter()
    h = np.asarray(x)
    flight = _begin_op("host_allgather", "host", h.nbytes)
    if jax.process_count() == 1:
        out = h[None]
        _record_op("host_allgather", "host", h.nbytes, t0, flight)
        return out
    try:  # pragma: no cover - multihost only
        from jax.experimental import multihost_utils

        out = np.asarray(multihost_utils.process_allgather(h, tiled=False))
    except BaseException:  # pragma: no cover - multihost only
        _abort_op(flight)
        raise
    _record_op("host_allgather", "host", h.nbytes, t0, flight)
    return out


def host_bcast(x: Any, root: int = 0) -> np.ndarray:
    """Broadcast a per-process host value from the root process to all."""
    if _faults.ARMED:
        _faults.check("comm.host_bcast")
    t0 = time.perf_counter()
    h = np.asarray(x)
    flight = _begin_op("host_bcast", "host", h.nbytes)
    if jax.process_count() == 1:
        _record_op("host_bcast", "host", h.nbytes, t0, flight)
        return h
    try:  # pragma: no cover - multihost only
        from jax.experimental import multihost_utils

        out = np.asarray(
            multihost_utils.broadcast_one_to_all(
                h, is_source=jax.process_index() == root
            )
        )
    except BaseException:  # pragma: no cover - multihost only
        _abort_op(flight)
        raise
    _record_op("host_bcast", "host", h.nbytes, t0, flight)
    return out
