"""Rank-aware, timestamped printing.

TPU-native analogue of the reference's metaprogrammed ``fluxmpi_print`` /
``fluxmpi_println`` pair (reference: src/common.jl:72-112):

- pre-init: timestamp-only prefix (src/common.jl:76-79);
- single worker: plain print (src/common.jl:82-85);
- multi-process world: timestamp + ``[rank / size]`` prefix, output
  serialized across processes by looping ranks with a barrier between each
  (src/common.jl:86-92). On TPU the barrier is a host-level global sync
  (``multihost_utils.sync_global_devices``) rather than ``MPI.Barrier``;
  within one controller process there is nothing to serialize.

These functions do host-side IO only and are never traced — the analogue of
the reference's ``@non_differentiable`` marks (src/common.jl:96).
"""

from __future__ import annotations

import datetime
import itertools
import sys
from typing import Any

import jax

from .runtime import is_initialized

__all__ = ["fluxmpi_print", "fluxmpi_println"]

_print_counter = itertools.count()


def _now() -> str:
    return datetime.datetime.now().isoformat(sep=" ", timespec="milliseconds")


def _barrier(tag: str) -> None:
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(tag)


def _rank_print(*args: Any, end: str, **kwargs: Any) -> None:
    if not is_initialized():
        print(f"{_now()} ", *args, end=end, **kwargs)
        return
    rank = jax.process_index()
    size = jax.process_count()
    if size == 1:
        print(*args, end=end, **kwargs)
        return
    # Serialize output across processes: each rank prints in turn with a
    # global barrier between turns (reference: src/common.jl:86-92).
    tag = f"fluxmpi_print_{next(_print_counter)}"
    for r in range(size):
        if r == rank:
            print(f"{_now()} [{rank} / {size}] ", *args, end=end, **kwargs)
            sys.stdout.flush()
        _barrier(f"{tag}_{r}")


def fluxmpi_print(*args: Any, **kwargs: Any) -> None:
    """Print with timestamp + ``[rank / size]`` prefix, serialized across
    processes (reference: src/common.jl:72-112)."""
    _rank_print(*args, end=kwargs.pop("end", ""), **kwargs)


def fluxmpi_println(*args: Any, **kwargs: Any) -> None:
    """:func:`fluxmpi_print` with a trailing newline
    (reference ``fluxmpi_println``, src/common.jl:72-112)."""
    _rank_print(*args, end=kwargs.pop("end", "\n"), **kwargs)
