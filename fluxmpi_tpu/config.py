"""Persistent configuration / preferences.

TPU-native analogue of the reference's Preferences.jl-backed config surface
(reference: src/FluxMPI.jl:16-31, 51-56). The reference persists a single flag
(``FluxMPIDisableCUDAMPISupport``) to LocalPreferences.toml, reads it once at
module ``__init__``, and warns on a deprecated env var. On TPU the
CUDA-aware-vs-CPU-staging dichotomy disappears (device buffers are always
collective-capable over ICI), so the analogous knobs here govern the things
that actually matter on TPU: whether eager host-level collectives stage
through the host instead of running on the device mesh, buffer donation in
compiled train steps, and the default mesh axis name.

Preferences are stored in a JSON file next to the consuming project
(``./LocalPreferences.json``, the direct analogue of LocalPreferences.toml),
overridable via ``FLUXMPI_TPU_PREFS`` and per-key env vars
``FLUXMPI_TPU_<KEY>``.
"""

from __future__ import annotations

import json
import os
import warnings
from typing import Any

_PREFS_ENV = "FLUXMPI_TPU_PREFS"
_PREFS_BASENAME = "LocalPreferences.json"
_PREFS_NAMESPACE = "fluxmpi_tpu"

# Reference parity: warn on the removed env var (src/FluxMPI.jl:17-19 warns on
# FLUXMPI_DISABLE_CUDAMPI_SUPPORT). That knob has no TPU meaning; we point
# users at the TPU-relevant replacement.
_DEPRECATED_ENV = "FLUXMPI_DISABLE_CUDAMPI_SUPPORT"

_DEFAULTS: dict[str, Any] = {
    # Force eager collectives to stage via host numpy instead of the device
    # mesh (debugging aid; the analogue of the reference's CPU-staging path,
    # src/mpi_extensions.jl:97-106).
    "disable_device_collectives": False,
    # Donate parameter/optimizer buffers in compiled train steps.
    "donate_buffers": True,
    # Default name of the data-parallel mesh axis.
    "dp_axis_name": "dp",
    # Default name of the FSDP/ZeRO mesh axis (parameter + optimizer
    # sharding in a composed ParallelConfig; dp-only layouts shard over
    # the data axis instead — see parallel/plan.py).
    "fsdp_axis_name": "fsdp",
    # Default name of the sequence-parallel mesh axis (ring attention).
    "sp_axis_name": "sp",
    # Default name of the tensor-parallel mesh axis (sharded matmuls).
    "tp_axis_name": "tp",
    # Default name of the expert-parallel mesh axis (MoE experts).
    "ep_axis_name": "ep",
    # Default name of the pipeline-parallel mesh axis (GPipe stages).
    "pp_axis_name": "pp",
}


def _prefs_path() -> str:
    return os.environ.get(_PREFS_ENV, os.path.join(os.getcwd(), _PREFS_BASENAME))


def _read_file() -> dict[str, Any]:
    path = _prefs_path()
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return {}
    ns = data.get(_PREFS_NAMESPACE, {})
    return ns if isinstance(ns, dict) else {}


def _coerce(value: str, like: Any) -> Any:
    if isinstance(like, bool):
        return value.strip().lower() in ("1", "true", "yes", "on")
    if isinstance(like, int):
        return int(value)
    if isinstance(like, float):
        return float(value)
    return value


def load_preference(key: str, default: Any = None) -> Any:
    """Read preference ``key``: env var > preferences file > default.

    Analogue of ``@load_preference`` (reference: src/FluxMPI.jl:21).
    """
    fallback = _DEFAULTS.get(key, default)
    env_key = f"FLUXMPI_TPU_{key.upper()}"
    if env_key in os.environ:
        return _coerce(os.environ[env_key], fallback)
    file_prefs = _read_file()
    if key in file_prefs:
        return file_prefs[key]
    return fallback


def set_preference(key: str, value: Any) -> None:
    """Persist preference ``key`` to the preferences file.

    Analogue of ``@set_preferences!`` (reference: src/FluxMPI.jl:53). Takes
    effect for values read after the call; module-level cached flags (see
    :func:`disable_device_collectives`) need a fresh session, matching the
    reference's restart requirement (src/FluxMPI.jl:55).
    """
    path = _prefs_path()
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
        if not isinstance(data, dict):
            data = {}
    except (FileNotFoundError, json.JSONDecodeError):
        data = {}
    data.setdefault(_PREFS_NAMESPACE, {})[key] = value
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")


def delete_preference(key: str) -> None:
    """Remove a persisted preference (no-op if absent)."""
    path = _prefs_path()
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return
    if isinstance(data, dict) and key in data.get(_PREFS_NAMESPACE, {}):
        del data[_PREFS_NAMESPACE][key]
        with open(path, "w", encoding="utf-8") as f:
            json.dump(data, f, indent=2, sort_keys=True)
            f.write("\n")


def disable_device_collectives() -> None:
    """Persistently force eager collectives onto the host-staging path.

    The TPU analogue of ``FluxMPI.disable_cudampi_support()``
    (reference: src/FluxMPI.jl:51-56): a persisted opt-out of the fast
    transport, requiring a session restart to take effect, kept as a
    debugging escape hatch.
    """
    set_preference("disable_device_collectives", True)
    warnings.warn(
        "Device-mesh collectives disabled for future sessions; restart the "
        "session for this to take effect.",
        stacklevel=2,
    )


def env_int(
    name: str,
    default: int | None = None,
    *,
    minimum: int | None = None,
) -> int | None:
    """ONE copy of the integer-env-knob parse with the warn-and-default
    convention (an env typo must degrade, never crash a job — the
    ``faults.configure`` rule). Unset/empty returns ``default``;
    garbage, or a value below ``minimum``, warns and returns
    ``default``. Shared by the serving plane's geometry knobs and the
    model-internals plane's depth/top-k knobs."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        value = int(raw)
    except ValueError:
        warnings.warn(
            f"ignoring {name}={raw!r}: not an integer"
            + (f" — using the default {default}" if default is not None else ""),
            stacklevel=3,
        )
        return default
    if minimum is not None and value < minimum:
        warnings.warn(
            f"ignoring {name}={raw!r}: must be >= {minimum}"
            + (f" — using the default {default}" if default is not None else ""),
            stacklevel=3,
        )
        return default
    return value


def _warn_deprecated_env() -> None:
    if _DEPRECATED_ENV in os.environ:
        warnings.warn(
            f"`{_DEPRECATED_ENV}` is ignored: there is no CUDA-aware-MPI "
            "dichotomy on TPU. Use "
            "`fluxmpi_tpu.config.disable_device_collectives()` if you need "
            "the host-staging debug path.",
            stacklevel=2,
        )


# Cached at import, mirroring the reference's read-once-at-__init__ semantics
# (src/FluxMPI.jl:21-31).
_warn_deprecated_env()
DEVICE_COLLECTIVES_DISABLED: bool = bool(load_preference("disable_device_collectives"))
DP_AXIS_NAME: str = str(load_preference("dp_axis_name"))
FSDP_AXIS_NAME: str = str(load_preference("fsdp_axis_name"))
SP_AXIS_NAME: str = str(load_preference("sp_axis_name"))
TP_AXIS_NAME: str = str(load_preference("tp_axis_name"))
EP_AXIS_NAME: str = str(load_preference("ep_axis_name"))
PP_AXIS_NAME: str = str(load_preference("pp_axis_name"))
