"""Gradient synchronization: DistributedOptimizer + allreduce_gradients.

TPU-native redesign of the reference's gradient layer
(reference: src/optimizer.jl). The reference offers two spellings:

- ``DistributedOptimizer`` — wraps any Optimisers.jl rule; each parameter
  leaf's gradient is (blocking) all-reduced inside ``apply!``
  (src/optimizer.jl:16-25);
- ``allreduce_gradients`` — the preferred overlapped path: one non-blocking
  ``Iallreduce!`` per leaf, single ``Waitall!`` (src/optimizer.jl:45-65).

Both spellings survive here, and both collapse to a single compiled XLA
AllReduce when used inside a jitted train step: call
``allreduce_gradients(grads, axis_name="dp")`` (or wrap your optax optimizer
in ``DistributedOptimizer(opt, axis_name="dp")``) inside ``shard_map``/pjit,
and XLA schedules the reduction asynchronously against the rest of the step —
the compiler-scheduled analogue of the reference's request/wait overlap.
Outside jit, the eager path fuses the whole gradient tree into ONE flat
collective (strictly better than the reference's per-leaf requests).

Semantics parity: gradients are **summed, not averaged** — scale your loss by
``1 / total_workers()`` (reference docstring note src/optimizer.jl:11-14,
changelog README.md:127-128). Pass ``reduce_op="mean"`` to opt into
averaging.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
import optax

from . import config
from .comm import host_allreduce

__all__ = ["DistributedOptimizer", "allreduce_gradients"]


def _is_traced(tree: Any) -> bool:
    return any(
        isinstance(leaf, jax.core.Tracer) for leaf in jax.tree_util.tree_leaves(tree)
    )


def _axis_is_bound(axis_name: str) -> bool:
    """Is ``axis_name`` a bound mesh axis in the current trace?"""
    try:
        jax.lax.axis_index(axis_name)
        return True
    except NameError:
        return False


def allreduce_gradients(
    grads: Any, *, axis_name: str | None = None, reduce_op: str = "sum"
) -> Any:
    """All-reduce a gradient pytree across all data-parallel workers.

    Reference: ``allreduce_gradients`` (src/optimizer.jl:45-65).

    Inside a jitted/shard_mapped step with a bound mesh axis, this is
    ``lax.psum(grads, axis_name)`` — one compiled, compiler-overlapped
    AllReduce over ICI (the analogue of the reference's Iallreduce+Waitall
    overlap, with the GPU→CPU staging of src/optimizer.jl:46-47 gone: ICI
    reduces device buffers directly).

    Outside jit, gradients held per controller process are summed across
    processes with ONE fused collective over the flattened tree (identity in
    a single-process world, where replicated device values cannot diverge).
    Leaves that are *device-sharded* (non-replicated over >1 device) are
    ambiguous here — an FSDP/TP-sharded gradient is already one global value
    needing no reduction, while a :func:`fluxmpi_tpu.shard_ranks`-stacked
    per-worker value needs a mesh-axis reduction — so they raise with
    guidance instead of silently passing through (VERDICT r1 weak #4): use
    :func:`fluxmpi_tpu.allreduce` for per-worker stacks, or call this inside
    the jitted step for per-device semantics.
    """
    if reduce_op not in ("sum", "mean"):
        raise ValueError("reduce_op must be 'sum' or 'mean'")

    if _is_traced(grads):
        name = axis_name or config.DP_AXIS_NAME
        if not _axis_is_bound(name):
            # Plain `jax.jit` with auto-sharding: XLA already inserts the
            # cross-device reduction as part of differentiating through the
            # sharded batch, so the gradients arriving here are the global
            # gradients — summing again would double-count. Identity.
            return grads
        red = jax.lax.psum(grads, name)
        if reduce_op == "mean":
            size = jax.lax.psum(1, name)
            red = jax.tree_util.tree_map(lambda g: g / size, red)
        return red

    # Eager host-level path: fuse the tree into one flat buffer per dtype —
    # one collective per dtype group instead of one per leaf, with no
    # precision-losing casts.
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    if not leaves:
        return grads

    # Device-sharded leaves are ambiguous in the eager path (see
    # docstring); refuse rather than guess — silently passing them through
    # (the r1 behavior) dropped genuinely divergent shard_ranks values, and
    # auto-reducing would corrupt FSDP/TP-sharded global gradients.
    for i, leaf in enumerate(leaves):
        if (
            isinstance(leaf, jax.Array)
            and len(leaf.sharding.device_set) > 1
            and not leaf.is_fully_replicated
        ):
            raise ValueError(
                "eager allreduce_gradients got a device-sharded leaf (index "
                f"{i}, shape {leaf.shape}, sharding {leaf.sharding}). A "
                "sharded array is one global value here, so there is "
                "nothing unambiguous to reduce: for per-worker stacked "
                "values use fluxmpi_tpu.allreduce; for per-device gradients "
                "call allreduce_gradients inside the jitted/shard_mapped "
                "train step."
            )

    if jax.process_count() == 1:
        return grads
    arrays = [np.asarray(jax.device_get(l)) for l in leaves]
    out_arrays: list[np.ndarray | None] = [None] * len(leaves)
    by_dtype: dict[np.dtype, list[int]] = {}
    for i, a in enumerate(arrays):
        by_dtype.setdefault(a.dtype, []).append(i)
    for dtype, idxs in by_dtype.items():
        flat = np.concatenate([arrays[i].ravel() for i in idxs])
        reduced = host_allreduce(flat, op="sum")
        if reduce_op == "mean":
            reduced = (reduced / jax.process_count()).astype(dtype)
        offset = 0
        for i in idxs:
            n = arrays[i].size
            out_arrays[i] = reduced[offset : offset + n].reshape(arrays[i].shape)
            offset += n
    out_leaves = []
    for leaf, chunk in zip(leaves, out_arrays):
        assert chunk is not None
        if isinstance(leaf, jax.Array):
            out_leaves.append(
                jax.device_put(jnp.asarray(chunk, dtype=leaf.dtype), leaf.sharding)
            )
        else:
            out_leaves.append(chunk.astype(np.asarray(leaf).dtype))
    return jax.tree_util.tree_unflatten(treedef, out_leaves)


class DistributedOptimizerState(NamedTuple):
    inner: Any


def DistributedOptimizer(
    optimizer: optax.GradientTransformation,
    *,
    axis_name: str | None = None,
    reduce_op: str = "sum",
) -> optax.GradientTransformation:
    """Wrap an optax optimizer so incoming gradients are all-reduced across
    the data-parallel workers before the inner update.

    Reference: ``DistributedOptimizer`` (src/optimizer.jl:16-25) wrapping any
    Optimisers.jl rule and all-reducing each leaf in ``apply!``. Here the
    wrapper is an :class:`optax.GradientTransformation`, the reduction is one
    fused collective over the whole tree, and ``init`` delegates to the inner
    optimizer (reference: src/optimizer.jl:25).

    Gradients are **summed** (scale your loss by ``1/total_workers()``,
    reference src/optimizer.jl:11-14) unless ``reduce_op="mean"``.
    """

    def init_fn(params):
        return DistributedOptimizerState(inner=optimizer.init(params))

    def update_fn(updates, state, params=None, **extra):
        updates = allreduce_gradients(
            updates, axis_name=axis_name, reduce_op=reduce_op
        )
        new_updates, inner_state = optimizer.update(updates, state.inner, params, **extra)
        return new_updates, DistributedOptimizerState(inner=inner_state)

    return optax.GradientTransformation(init_fn, update_fn)
