"""fluxlint — the repo's AST-based SPMD / hot-path invariant checker.

Pure stdlib (no jax): enforces statically the contracts the last several
PRs kept re-fixing by hand — every rank executes the same collective
sequence, instrumentation stays behind the zero-cost-when-off guard, and
the string registries (metric names, fault sites, ``FLUXMPI_TPU_*`` env
vars) stay in sync with ``telemetry/schema.py``, ``faults.KNOWN_SITES``,
and the docs table. Run it via ``scripts/fluxlint.py`` (which loads this
package standalone, no backend boot) or in-process::

    from fluxmpi_tpu.analysis import lint_repo
    report = lint_repo("/path/to/repo", ["fluxmpi_tpu", "scripts"])
    assert report.exit_code == 0, report.text()

Rule catalogue, suppression (``# fluxlint: disable=<rule>``) and
baseline workflow: docs/static_analysis.md.
"""

from __future__ import annotations

import os
from typing import Iterable

from .context import ProjectContext, load_schema_module
from .core import (
    BASELINE_BASENAME,
    JSON_SCHEMA,
    Baseline,
    Finding,
    ModuleSource,
    Report,
    Rule,
    lint_modules,
    parse_files,
)
from .rules import DEFAULT_HOT_FUNCTIONS, default_rules

__all__ = [
    "BASELINE_BASENAME",
    "JSON_SCHEMA",
    "Baseline",
    "DEFAULT_HOT_FUNCTIONS",
    "Finding",
    "ModuleSource",
    "ProjectContext",
    "Report",
    "Rule",
    "default_rules",
    "lint_modules",
    "lint_repo",
    "lint_source",
    "load_schema_module",
    "collect_py_files",
]


def collect_py_files(targets: Iterable[str], repo_root: str) -> list[str]:
    """Absolute paths of the ``.py`` files under ``targets`` (files or
    directories, absolute or repo-root-relative), ``__pycache__``
    pruned, sorted for stable reports."""
    out: list[str] = []
    for target in targets:
        path = (
            target
            if os.path.isabs(target)
            else os.path.join(repo_root, target)
        )
        if os.path.isfile(path):
            out.append(path)
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for name in filenames:
                if name.endswith(".py"):
                    out.append(os.path.join(dirpath, name))
    return sorted(out)


def lint_repo(
    repo_root: str,
    targets: Iterable[str] = ("fluxmpi_tpu", "scripts"),
    *,
    baseline_path: str | None = None,
    context: ProjectContext | None = None,
    rules: Iterable[Rule] | None = None,
) -> Report:
    """Lint ``targets`` under ``repo_root`` with the default rule set,
    project context, and baseline (``.fluxlint-baseline.json`` at the
    repo root unless overridden)."""
    repo_root = os.path.abspath(repo_root)
    ctx = context if context is not None else ProjectContext.load(repo_root)
    files = collect_py_files(targets, repo_root)

    def read(path: str) -> str:
        with open(path, encoding="utf-8") as f:
            return f.read()

    modules, errors = parse_files(files, repo_root, read)
    if baseline_path is None:
        baseline_path = os.path.join(repo_root, BASELINE_BASENAME)
    # An empty baseline_path means "no baseline" (every finding active).
    baseline = Baseline.load(baseline_path) if baseline_path else None
    report = lint_modules(
        modules,
        rules if rules is not None else default_rules(),
        ctx,
        baseline,
    )
    report.unreadable.extend(errors)
    return report


def lint_source(
    source: str,
    path: str,
    context: ProjectContext,
    rules: Iterable[Rule] | None = None,
    baseline: Baseline | None = None,
) -> Report:
    """Lint one in-memory source snippet as if it lived at ``path``
    (repo-relative) — the fixture-test entry point."""
    module = ModuleSource(path, source)
    return lint_modules(
        [module],
        rules if rules is not None else default_rules(),
        context,
        baseline,
    )
