"""The fluxlint rule set — seven invariants this repo has paid for.

Each rule's docstring names the contract it enforces and the bug class
(from CHANGES.md history) that motivates it; docs/static_analysis.md
carries the full catalogue with examples and the suppression workflow.
"""

from __future__ import annotations

import ast
import difflib
import re
from typing import Any, Iterator

from .core import Finding, ModuleSource, Rule
from .flow import (
    GUARD_OFF,
    GUARD_ON,
    classify_guard,
    guard_derived_names,
    rank_condition,
    rank_derived_names,
    terminal_name,
    terminates,
    value_root,
    walk_no_nested_functions,
)

# ---------------------------------------------------------------------------
# Collective-call matching (shared by the SPMD rule)
# ---------------------------------------------------------------------------

# comm.<attr> / _comm.<attr> — the eager collective surface.
_COMM_ATTRS = frozenset(
    {
        "allreduce",
        "bcast",
        "reduce",
        "iallreduce",
        "ibcast",
        "barrier",
        "host_allreduce",
        "host_allgather",
        "host_bcast",
    }
)

# <anything>.<attr> — names specific enough to match on any receiver
# (multihost_utils, checkpoint module objects, ...).
_ANY_ATTRS = frozenset(
    {
        "host_allreduce",
        "host_allgather",
        "host_bcast",
        "save_checkpoint",
        "restore_checkpoint",
        "sync_global_devices",
        "sync_global_processes",
        "broadcast_one_to_all",
        "process_allgather",
    }
)

# Bare names (from-imports / module-local helpers). `reduce` is absent on
# purpose: bare `reduce` is functools territory.
_BARE_NAMES = _ANY_ATTRS | frozenset(
    {
        "allreduce",
        "bcast",
        "iallreduce",
        "ibcast",
        "barrier",
        "synchronize",
        "_process_barrier",
    }
)


def _collective_call(node: ast.Call) -> str | None:
    """The collective's name when ``node`` is a cross-process
    rendezvous every rank must reach; None otherwise."""
    func = node.func
    if isinstance(func, ast.Attribute):
        root = value_root(func)
        if func.attr in _COMM_ATTRS and root in ("comm", "_comm"):
            return func.attr
        if func.attr in _ANY_ATTRS:
            return func.attr
        return None
    if isinstance(func, ast.Name) and func.id in _BARE_NAMES:
        return func.id
    return None


def _functions_with_qualnames(
    tree: ast.AST,
) -> Iterator[tuple[str, ast.AST]]:
    """Yield every function definition with its dotted qualname
    (``Class.method`` / ``outer.inner``)."""

    def visit(node: ast.AST, prefix: str) -> Iterator[tuple[str, ast.AST]]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                yield qual, child
                yield from visit(child, qual + ".")
            elif isinstance(child, ast.ClassDef):
                yield from visit(child, f"{prefix}{child.name}.")
            else:
                yield from visit(child, prefix)

    yield from visit(tree, "")


class SpmdDivergentCollective(Rule):
    """A collective reachable by only a subset of ranks is a hang, not a
    bug report: the excluded ranks never join the rendezvous and the
    fleet wedges inside XLA (the PR 5/6 class — lead-only code stranding
    peers at a barrier, fixed post-review in both).

    Two shapes are flagged, per function:

    1. a collective call nested (at any depth, nested defs excluded)
       under a rank-conditional branch — ``if jax.process_index() == 0:``
       and friends, including through a local bool
       (``lead = process_index() == 0``);
    2. a rank-conditional branch that *exits* (return/raise) followed —
       later in the same block — by a collective: the exiting ranks
       never reach it.

    World-size conditions (``process_count() > 1``) are SPMD-consistent
    and never flagged.
    """

    id = "spmd-divergent-collective"
    severity = "error"
    description = "collective reachable only under a rank-conditional branch"

    def check(self, module: ModuleSource, ctx: Any) -> Iterator[Finding]:
        for qual, fn in _functions_with_qualnames(module.tree):
            rank_names = rank_derived_names(fn)
            yield from self._scan_block(module, qual, fn.body, rank_names)
            yield from self._scan_expressions(module, qual, fn, rank_names)

    def _scan_expressions(
        self,
        module: ModuleSource,
        qual: str,
        fn: ast.AST,
        rank_names: set[str],
    ) -> Iterator[Finding]:
        """Rank-conditional *expressions* that gate a collective: the
        short-circuit form (``rank_ok and comm.allreduce(x)`` — the
        collective runs only where the left operand is true) and the
        conditional form (``comm.barrier() if lead else None``)."""
        for node in walk_no_nested_functions(fn):
            if isinstance(node, ast.BoolOp) and isinstance(node.op, ast.And):
                seen_rank = False
                for value in node.values:
                    if seen_rank:
                        for call in self._collectives_in_expr(value):
                            name = _collective_call(call)
                            yield self.finding(
                                module.path,
                                call,
                                f"collective {name}() in {qual} is "
                                f"short-circuited by a rank-conditional "
                                f"operand (line {node.lineno}): only a "
                                f"subset of ranks evaluates it — the rest "
                                f"never join the rendezvous",
                                f"{qual}:{name}:shortcircuit",
                            )
                    if rank_condition(value, rank_names):
                        seen_rank = True
            elif isinstance(node, ast.IfExp) and rank_condition(
                node.test, rank_names
            ):
                for arm in (node.body, node.orelse):
                    for call in self._collectives_in_expr(arm):
                        name = _collective_call(call)
                        yield self.finding(
                            module.path,
                            call,
                            f"collective {name}() in {qual} sits in a "
                            f"rank-conditional conditional expression "
                            f"(line {node.lineno}) — only a subset of "
                            f"ranks evaluates it",
                            f"{qual}:{name}:shortcircuit",
                        )

    def _collectives_in_expr(self, expr: ast.expr) -> Iterator[ast.Call]:
        for node in walk_no_nested_functions(expr):
            if isinstance(node, ast.Call) and _collective_call(node):
                yield node

    def _collectives_in(self, stmts: list[ast.stmt]) -> Iterator[ast.Call]:
        for stmt in stmts:
            for node in walk_no_nested_functions(stmt):
                if isinstance(node, ast.Call):
                    if _collective_call(node) is not None:
                        yield node

    def _scan_block(
        self,
        module: ModuleSource,
        qual: str,
        block: list[ast.stmt],
        rank_names: set[str],
    ) -> Iterator[Finding]:
        diverged_at: ast.If | None = None
        for stmt in block:
            if isinstance(stmt, ast.If) and rank_condition(
                stmt.test, rank_names
            ):
                for call in self._collectives_in(stmt.body + stmt.orelse):
                    name = _collective_call(call)
                    yield self.finding(
                        module.path,
                        call,
                        f"collective {name}() inside a rank-conditional "
                        f"branch (condition at line {stmt.lineno}) in "
                        f"{qual}: ranks that skip the branch never join "
                        f"the rendezvous — hoist the collective out, or "
                        f"make the condition SPMD-consistent",
                        f"{qual}:{name}:branch",
                    )
                body_exits = terminates(stmt.body) and not terminates(
                    stmt.orelse or []
                )
                orelse_exits = bool(stmt.orelse) and terminates(
                    stmt.orelse
                ) and not terminates(stmt.body)
                if (body_exits or orelse_exits) and diverged_at is None:
                    diverged_at = stmt
                continue
            if diverged_at is not None:
                for call in self._collectives_in([stmt]):
                    name = _collective_call(call)
                    yield self.finding(
                        module.path,
                        call,
                        f"collective {name}() in {qual} is unreachable "
                        f"for ranks that exited at the rank-conditional "
                        f"early return/raise on line "
                        f"{diverged_at.lineno} — the remaining ranks "
                        f"hang at the rendezvous",
                        f"{qual}:{name}:after-exit",
                    )
            # Recurse into compound statements (their inner blocks get
            # their own early-exit tracking).
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if sub and not isinstance(
                    stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
                ):
                    if isinstance(stmt, ast.If) and rank_condition(
                        stmt.test, rank_names
                    ):
                        continue  # already reported above
                    yield from self._scan_block(
                        module, qual, sub, rank_names
                    )
            for handler in getattr(stmt, "handlers", []) or []:
                yield from self._scan_block(
                    module, qual, handler.body, rank_names
                )


# ---------------------------------------------------------------------------
# Rule 2: unguarded hot-path instrumentation
# ---------------------------------------------------------------------------

# (path suffix, dotted qualname, scope) — scope "function" scans the
# whole body; "loops" scans only loop bodies (drivers like train_loop
# legitimately time at run/flush granularity outside the dispatch loop).
DEFAULT_HOT_FUNCTIONS: tuple[tuple[str, str, str], ...] = (
    ("fluxmpi_tpu/comm.py", "_run_collective", "function"),
    ("fluxmpi_tpu/comm.py", "barrier", "function"),
    ("fluxmpi_tpu/parallel/loop.py", "train_loop", "loops"),
    ("fluxmpi_tpu/data.py", "DistributedDataLoader._timed_batches", "function"),
    ("fluxmpi_tpu/data.py", "DistributedDataLoader.__iter__", "function"),
    ("fluxmpi_tpu/data.py", "DistributedDataLoader._iter_batches", "function"),
)

_TIME_ATTRS = frozenset(
    {"perf_counter", "time", "monotonic", "process_time", "thread_time"}
)

# Attribute-call names that resolve registry handles, record into them,
# or talk to the tracer/flight recorder. `get_tracer`/`_flight_recorder`
# are deliberately absent: fetching the object to READ `.enabled` is how
# the guard itself is resolved; recording through it trips `.instant` /
# `.add_complete_event` / the comm helpers instead.
_INSTR_ATTRS = frozenset(
    {
        "counter",
        "gauge",
        "histogram",
        "observe",
        "instant",
        "add_complete_event",
        "segment",
    }
)

# Module-local instrumentation helpers (comm.py's flight/trace plumbing).
_INSTR_EXTRA = frozenset({"_begin_op", "_record_op", "_abort_op"})


def _instr_call(node: ast.Call) -> str | None:
    func = node.func
    name = terminal_name(func)
    if name is None:
        return None
    if isinstance(func, ast.Attribute):
        if name in _TIME_ATTRS and value_root(func) == "time":
            return f"time.{name}"
        if name in _INSTR_ATTRS or name in _INSTR_EXTRA:
            return name
        return None
    if name == "perf_counter" or name in _INSTR_EXTRA:
        return name
    if name in ("add_complete_event", "instant"):
        return name
    return None


class UnguardedHotPathInstrumentation(Rule):
    """The PR 4 zero-cost-when-off contract: with telemetry, tracing,
    and the flight recorder all disabled, the designated hot paths
    (``comm._run_collective``, the ``train_loop`` dispatch loop, the
    loader's batch iterators) perform **no** ``perf_counter`` reads, no
    registry-handle lookups, and no tracer calls. Every instrumentation
    call there must be dominated by the fast-guard —
    ``_instrumentation_on()``, an ``.enabled`` read, or a local bool
    resolved from one (``instrumented`` / ``gp_on``) — either by
    enclosing ``if guard:`` or by an early ``if not guard: return``.
    """

    id = "unguarded-hot-path-instrumentation"
    severity = "error"
    description = "instrumentation call on a hot path without the fast-guard"

    def __init__(
        self,
        hot_functions: tuple[tuple[str, str, str], ...] = DEFAULT_HOT_FUNCTIONS,
    ):
        self.hot_functions = hot_functions

    def check(self, module: ModuleSource, ctx: Any) -> Iterator[Finding]:
        hot = {
            qual: scope
            for suffix, qual, scope in self.hot_functions
            if module.path.endswith(suffix)
        }
        if not hot:
            return
        for qual, fn in _functions_with_qualnames(module.tree):
            scope = hot.get(qual)
            if scope is None:
                continue
            guard_names = guard_derived_names(fn)
            if scope == "function":
                yield from self._scan_block(
                    module, qual, fn.body, guard_names, False
                )
            else:
                # loops: only the OUTERMOST For/While bodies — each is
                # scanned with full recursion so inner loops keep the
                # guard context of their enclosing branches (scanning
                # every loop independently would both drop that context
                # and double-report nested violations).
                for node in self._outermost_loops(fn.body):
                    guarded = isinstance(
                        node, ast.While
                    ) and classify_guard(node.test, guard_names) == GUARD_ON
                    yield from self._scan_block(
                        module, qual, node.body, guard_names, guarded
                    )

    def _outermost_loops(
        self, block: list[ast.stmt]
    ) -> Iterator[ast.For | ast.While]:
        for stmt in block:
            if isinstance(stmt, (ast.For, ast.While)):
                yield stmt  # do not descend: inner loops ride along
                continue
            if isinstance(
                stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if sub:
                    yield from self._outermost_loops(sub)
            for handler in getattr(stmt, "handlers", []) or []:
                yield from self._outermost_loops(handler.body)

    # -- statement walk with guard state --------------------------------

    def _scan_block(
        self,
        module: ModuleSource,
        qual: str,
        block: list[ast.stmt],
        guard_names: dict[str, str],
        guarded: bool,
    ) -> Iterator[Finding]:
        # _scan_expr reads the guard names from this slot so the
        # expression walk keeps a flat signature.
        self._guard_names = guard_names
        for stmt in block:
            if isinstance(
                stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue
            if isinstance(stmt, ast.If):
                cls = classify_guard(stmt.test, guard_names)
                yield from self._scan_expr(
                    module, qual, stmt.test, guarded
                )
                yield from self._scan_block(
                    module, qual, stmt.body, guard_names,
                    guarded or cls == GUARD_ON,
                )
                yield from self._scan_block(
                    module, qual, stmt.orelse, guard_names,
                    guarded or cls == GUARD_OFF,
                )
                if cls == GUARD_OFF and terminates(stmt.body):
                    guarded = True
                if cls == GUARD_ON and stmt.orelse and terminates(stmt.orelse):
                    guarded = True
                continue
            if isinstance(stmt, (ast.For, ast.While)):
                inner = guarded
                if isinstance(stmt, ast.While):
                    yield from self._scan_expr(
                        module, qual, stmt.test, guarded
                    )
                    if classify_guard(stmt.test, guard_names) == GUARD_ON:
                        inner = True
                else:
                    yield from self._scan_expr(
                        module, qual, stmt.iter, guarded
                    )
                yield from self._scan_block(
                    module, qual, stmt.body, guard_names, inner
                )
                yield from self._scan_block(
                    module, qual, stmt.orelse, guard_names, guarded
                )
                continue
            if isinstance(stmt, ast.Try):
                yield from self._scan_block(
                    module, qual, stmt.body, guard_names, guarded
                )
                for handler in stmt.handlers:
                    yield from self._scan_block(
                        module, qual, handler.body, guard_names, guarded
                    )
                yield from self._scan_block(
                    module, qual, stmt.orelse, guard_names, guarded
                )
                yield from self._scan_block(
                    module, qual, stmt.finalbody, guard_names, guarded
                )
                continue
            if isinstance(stmt, ast.With):
                for item in stmt.items:
                    yield from self._scan_expr(
                        module, qual, item.context_expr, guarded
                    )
                yield from self._scan_block(
                    module, qual, stmt.body, guard_names, guarded
                )
                continue
            # Plain statement: scan its expressions.
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    yield from self._scan_expr(module, qual, child, guarded)

    # -- expression walk honoring IfExp / short-circuit guards -----------

    def _scan_expr(
        self, module: ModuleSource, qual: str, expr: ast.expr, guarded: bool
    ) -> Iterator[Finding]:
        guard_names = self._guard_names
        if isinstance(expr, ast.IfExp):
            cls = classify_guard(expr.test, guard_names)
            yield from self._scan_expr(module, qual, expr.test, guarded)
            yield from self._scan_expr(
                module, qual, expr.body, guarded or cls == GUARD_ON
            )
            yield from self._scan_expr(
                module, qual, expr.orelse, guarded or cls == GUARD_OFF
            )
            return
        if isinstance(expr, ast.BoolOp) and isinstance(expr.op, ast.And):
            g = guarded
            for v in expr.values:
                yield from self._scan_expr(module, qual, v, g)
                if classify_guard(v, guard_names) == GUARD_ON:
                    g = True
            return
        if isinstance(expr, ast.Call):
            name = _instr_call(expr)
            if name is not None and not guarded:
                yield self.finding(
                    module.path,
                    expr,
                    f"{name}() in hot path {qual} is not dominated by the "
                    f"instrumentation fast-guard (_instrumentation_on() / "
                    f"a resolved .enabled bool) — the fully-off path must "
                    f"pay no timing or registry work",
                    f"{qual}:{name}",
                )
            for child in ast.iter_child_nodes(expr):
                if isinstance(child, (ast.expr, ast.keyword)):
                    sub = child.value if isinstance(child, ast.keyword) else child
                    yield from self._scan_expr(module, qual, sub, guarded)
            return
        if isinstance(expr, ast.Lambda):
            return
        for child in ast.iter_child_nodes(expr):
            if isinstance(child, ast.expr):
                yield from self._scan_expr(module, qual, child, guarded)

    _guard_names: dict[str, str] = {}


# ---------------------------------------------------------------------------
# Rule 3: unknown metric name
# ---------------------------------------------------------------------------


def _const_prefix(expr: ast.expr) -> str | None:
    """Constant leading prefix of a dynamic string build (``"a." + x``,
    f-string with a literal head); None when nothing constant leads."""
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        left = expr.left
        if isinstance(left, ast.Constant) and isinstance(left.value, str):
            return left.value
        return _const_prefix(left)
    if isinstance(expr, ast.JoinedStr) and expr.values:
        head = expr.values[0]
        if isinstance(head, ast.Constant) and isinstance(head.value, str):
            return head.value
    return None


def _docstring_constants(tree: ast.AST) -> set[int]:
    """ids of the Constant nodes that are module/class/function
    docstrings — prose naming a metric is documentation, not a read."""
    out: set[int] = set()
    for node in ast.walk(tree):
        if isinstance(
            node,
            (ast.Module, ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
        ):
            body = node.body
            if (
                body
                and isinstance(body[0], ast.Expr)
                and isinstance(body[0].value, ast.Constant)
                and isinstance(body[0].value.value, str)
            ):
                out.add(id(body[0].value))
    return out


class UnknownMetricName(Rule):
    """Metric-name literals handed to ``counter()``/``gauge()``/
    ``histogram()`` must come from ``schema.KNOWN_METRIC_NAMES`` — the
    names are the JSONL contract ``check_metrics_schema.py`` validates,
    and a producer-side typo (the drift class the closed ``fault.`` /
    ``checkpoint.`` / ``goodput.`` / ``anomaly.`` namespaces were
    created to stop) otherwise only surfaces when a consumer's dashboard
    goes blank. ``instant()`` trace-event names check against the same
    schema constants (``PREEMPTION_EVENT``, the ``anomaly.`` prefix).
    Dynamic names are skipped unless their constant prefix sits in a
    closed namespace with no known name under it.

    **Consumer side**: the dashboards under ``scripts/``
    (``fluxmpi_top``, ``goodput_report``, ``modelstats_report``) read
    metric keys as PLAIN string literals — no instrument call to hook —
    so a key that drifts from the schema there fails only at runtime,
    as a silently blank panel. Any string literal in a ``scripts/``
    module that is *shaped* like a metric name (dotted lowercase) and
    whose first segment names a known metric family must itself be a
    schema-known name or a family prefix (the ``"monitor."``
    ``startswith`` idiom). Dotted strings outside the known families
    (module paths, file suffixes) are ignored, as are docstrings."""

    id = "unknown-metric-name"
    severity = "error"
    description = "metric/trace name not in telemetry/schema.py"

    def check(self, module: ModuleSource, ctx: Any) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not isinstance(func, ast.Attribute) or not node.args:
                continue
            if func.attr in ("counter", "gauge", "histogram"):
                yield from self._check_metric(module, node, ctx)
            elif func.attr == "instant":
                yield from self._check_instant(module, node, ctx)
        if module.path.startswith("scripts/"):
            yield from self._check_consumer_literals(module, ctx)

    _METRIC_SHAPE_RE = re.compile(
        r"[a-z][a-z0-9_]*(?:\.[a-z0-9_]+)+\.?"
    )

    def _check_consumer_literals(
        self, module: ModuleSource, ctx: Any
    ) -> Iterator[Finding]:
        known = ctx.known_metric_names
        allowed = set(known) | {ctx.preemption_event}
        families = {name.split(".", 1)[0] + "." for name in known}
        docstrings = _docstring_constants(module.tree)
        seen: set[str] = set()
        for node in ast.walk(module.tree):
            if not (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
            ):
                continue
            if id(node) in docstrings:
                continue
            text = node.value
            if not self._METRIC_SHAPE_RE.fullmatch(text):
                continue
            if text in allowed or text.startswith(ctx.anomaly_event_prefix):
                continue
            if text.split(".", 1)[0] + "." not in families:
                continue  # dotted, but not a metric-family string
            if text.endswith("."):
                # Prefix reads ('monitor.', used with startswith) are
                # fine when some known name lives under the prefix; a
                # family-shaped prefix nothing lives under (a
                # trailing-dot typo like 'train.loss.', a sub-namespace
                # that was renamed away) is the same blank-panel drift
                # as a full-name typo.
                if any(k.startswith(text) for k in allowed):
                    continue
            key = text if not text.endswith(".") else f"prefix:{text}"
            if key in seen:
                continue
            seen.add(key)
            close = difflib.get_close_matches(text, known, n=1)
            hint = f" (did you mean {close[0]!r}?)" if close else ""
            yield self.finding(
                module.path,
                node,
                f"metric key literal {text!r} consumed here is not in "
                f"telemetry/schema.py KNOWN_METRIC_NAMES{hint} — a "
                f"dashboard reading an unknown key goes blank at "
                f"runtime; fix the key or add it to the schema",
                key,
            )

    def _check_metric(
        self, module: ModuleSource, node: ast.Call, ctx: Any
    ) -> Iterator[Finding]:
        arg = node.args[0]
        known = ctx.known_metric_names
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            name = arg.value
            if name in known:
                return
            close = difflib.get_close_matches(name, known, n=1)
            hint = f" (did you mean {close[0]!r}?)" if close else ""
            yield self.finding(
                module.path,
                node,
                f"metric name {name!r} is not in "
                f"telemetry/schema.py KNOWN_METRIC_NAMES{hint} — add it "
                f"to the schema (and the docs table) or fix the typo",
                name,
            )
            return
        prefix = _const_prefix(arg)
        if prefix and prefix.startswith(tuple(ctx.closed_namespaces)):
            if not any(k.startswith(prefix) for k in known):
                yield self.finding(
                    module.path,
                    node,
                    f"dynamic metric name with constant prefix {prefix!r} "
                    f"sits in a closed namespace but matches no known "
                    f"metric — closed-namespace names must be enumerable "
                    f"in the schema",
                    f"prefix:{prefix}",
                )

    def _check_instant(
        self, module: ModuleSource, node: ast.Call, ctx: Any
    ) -> Iterator[Finding]:
        arg = node.args[0]
        allowed = set(ctx.known_metric_names) | {ctx.preemption_event}
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            name = arg.value
            if name in allowed or name.startswith(ctx.anomaly_event_prefix):
                return
            yield self.finding(
                module.path,
                node,
                f"trace instant name {name!r} is not a schema-known "
                f"event (KNOWN_METRIC_NAMES, PREEMPTION_EVENT, or the "
                f"{ctx.anomaly_event_prefix!r} family) — the validator "
                f"will reject streams carrying it",
                name,
            )
            return
        prefix = _const_prefix(arg)
        if prefix and not (
            prefix.startswith(ctx.anomaly_event_prefix)
            or any(k.startswith(prefix) for k in allowed)
        ):
            yield self.finding(
                module.path,
                node,
                f"dynamic trace instant with constant prefix {prefix!r} "
                f"matches no schema-known event family",
                f"prefix:{prefix}",
            )


# ---------------------------------------------------------------------------
# Rule 4: unregistered fault site
# ---------------------------------------------------------------------------


class UnregisteredFaultSite(Rule):
    """``faults.check("...")`` literals must name a site registered in
    ``faults.KNOWN_SITES`` — an unregistered site is a chaos hook no
    schedule can reach by its documented name (and, since the registry
    feeds ``install()`` validation, a site string that drifts from the
    registry silently disarms every schedule targeting it). The project
    half of the rule closes the loop the other way: every registered
    site must be exercised by at least one test (substring grep over
    ``tests/`` at lint time), so the registry cannot accrete sites whose
    failure path nothing proves."""

    id = "unregistered-fault-site"
    severity = "error"
    description = "faults.check() site not in the canonical registry"

    def check(self, module: ModuleSource, ctx: Any) -> Iterator[Finding]:
        sites = ctx.known_fault_sites
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            func = node.func
            if not (
                isinstance(func, ast.Attribute)
                and func.attr == "check"
                and value_root(func) in ("faults", "_faults")
            ):
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                site = arg.value
                if site in sites:
                    continue
                close = difflib.get_close_matches(site, sites, n=1)
                hint = f" (nearest: {close[0]!r})" if close else ""
                yield self.finding(
                    module.path,
                    node,
                    f"fault site {site!r} is not registered in "
                    f"faults.KNOWN_SITES{hint} — register it (and add a "
                    f"test exercising it) or fix the name",
                    site,
                )
            else:
                prefix = _const_prefix(arg)
                if prefix and not any(s.startswith(prefix) for s in sites):
                    yield self.finding(
                        module.path,
                        node,
                        f"dynamic fault site with constant prefix "
                        f"{prefix!r} matches no registered site",
                        f"prefix:{prefix}",
                    )

    def project_check(
        self, modules: list[ModuleSource], ctx: Any
    ) -> Iterator[Finding]:
        if not ctx.tests_corpus:
            return
        for site in sorted(ctx.known_fault_sites):
            if site not in ctx.tests_corpus:
                yield Finding(
                    self.id,
                    self.severity,
                    ctx.faults_path,
                    0,
                    0,
                    f"registered fault site {site!r} is not exercised by "
                    f"any test under tests/ — a chaos hook nothing proves "
                    f"is dead weight; add a faults.scope() test or drop "
                    f"the site",
                    f"untested:{site}",
                )


# ---------------------------------------------------------------------------
# Rule 5: hand-built mesh / hard-coded axis names
# ---------------------------------------------------------------------------

# Call names whose string arguments ARE mesh axis names: the sharding
# spec constructors and the in-jit collectives bound to a named axis.
_AXIS_CONSUMER_NAMES = frozenset({"P", "PartitionSpec"})
_AXIS_COLLECTIVE_ATTRS = frozenset(
    {
        "psum",
        "pmean",
        "pmax",
        "pmin",
        "ppermute",
        "pshuffle",
        "all_gather",
        "all_to_all",
        "axis_index",
        "axis_size",
    }
)
# Keyword names that carry an axis name in any call signature.
_AXIS_KEYWORDS = frozenset(
    {
        "axis_name",
        "batch_axis_name",
        "dp_axis",
        "fsdp_axis",
        "tp_axis",
        "pp_axis",
        "sp_axis",
        "ep_axis",
    }
)


class HandBuiltMesh(Rule):
    """The ParallelConfig composition contract (parallel/plan.py): ONE
    mesh, resolved from ONE declarative plan — framework modules must
    not regrow private meshes or hard-code mesh-axis-name literals, the
    island-forming habit the plan engine exists to end (each of
    sharding/pipeline/ring/ulysses once built its own mesh and axis
    names, so ``dp × fsdp × tp × pp × sp`` could not compose).

    Flagged, for modules under ``fluxmpi_tpu/`` other than the plan
    engine itself (``parallel/plan.py``), the runtime (``runtime.py`` —
    the one place the global mesh is constructed), and the axis-name
    registry (``config.py``):

    1. any ``Mesh(...)`` construction;
    2. a default-axis-name literal (the ``*_axis_name`` values of
       ``config._DEFAULTS`` — ``"dp"``/``"tp"``/... today) passed to a
       ``PartitionSpec``/``P`` constructor, a named-axis collective
       (``jax.lax.psum`` and friends), or any ``axis_name=``-family
       keyword. Spell it ``config.DP_AXIS_NAME`` (or resolve it from
       the plan via ``plan_axis_name``) so a renamed axis — or a
       composed plan with different names — reaches every module.
    """

    id = "hand-built-mesh"
    severity = "error"
    description = "hand-built Mesh / hard-coded axis-name literal outside plan.py"

    _ALLOWED = (
        "fluxmpi_tpu/parallel/plan.py",
        "fluxmpi_tpu/runtime.py",
        "fluxmpi_tpu/config.py",
    )

    def check(self, module: ModuleSource, ctx: Any) -> Iterator[Finding]:
        if not module.path.startswith("fluxmpi_tpu/"):
            return
        if module.path in self._ALLOWED:
            return
        axis_literals = getattr(ctx, "axis_name_literals", frozenset())
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            name = terminal_name(func)
            if name == "Mesh":
                yield self.finding(
                    module.path,
                    node,
                    f"hand-built jax.sharding.Mesh in {module.path} — "
                    f"meshes come from ONE ParallelConfig "
                    f"(fluxmpi_tpu.init(parallel=) / "
                    f"ParallelConfig.resolve()); a private mesh re-forms "
                    f"the parallelism islands the plan engine removed",
                    "mesh",
                )
                continue
            if not axis_literals:
                continue
            # Both spellings consume axis names: jax.lax.psum(x, "dp")
            # (Attribute) and `from jax.lax import psum; psum(x, "dp")`
            # (Name).
            consumes_axes = (
                name in _AXIS_CONSUMER_NAMES
                or name in _AXIS_COLLECTIVE_ATTRS
                or (
                    isinstance(func, ast.Attribute)
                    and (
                        func.attr in _AXIS_COLLECTIVE_ATTRS
                        or func.attr in _AXIS_CONSUMER_NAMES
                    )
                )
            )
            checked: list[ast.expr] = []
            if consumes_axes:
                checked.extend(node.args)
            for kw in node.keywords:
                if kw.arg in _AXIS_KEYWORDS:
                    checked.append(kw.value)
            for arg in checked:
                for lit in self._axis_literals_in(arg, axis_literals):
                    yield self.finding(
                        module.path,
                        lit,
                        f"hard-coded mesh axis name {lit.value!r} — use "
                        f"the config *_AXIS_NAME constant (or "
                        f"plan_axis_name) so composed ParallelConfig "
                        f"layouts and renamed axes reach this call",
                        f"axis:{lit.value}",
                    )

    @staticmethod
    def _axis_literals_in(
        expr: ast.expr, axis_literals: frozenset[str]
    ) -> Iterator[ast.Constant]:
        if isinstance(expr, ast.Constant) and expr.value in axis_literals:
            yield expr
        elif isinstance(expr, (ast.Tuple, ast.List)):
            for elt in expr.elts:
                if (
                    isinstance(elt, ast.Constant)
                    and elt.value in axis_literals
                ):
                    yield elt


# ---------------------------------------------------------------------------
# Rule 6: undocumented env var
# ---------------------------------------------------------------------------


class UndocumentedEnvVar(Rule):
    """Every ``FLUXMPI_TPU_*`` variable the code reads must have a row
    in the docs/observability.md reference table, and every table row
    must correspond to a variable some code actually reads (scan set
    plus ``bench.py``) — the table was created precisely because these
    knobs kept drifting across five doc pages, and a one-sided check
    would let it rot back."""

    id = "undocumented-env-var"
    severity = "error"
    description = "FLUXMPI_TPU_* var missing from the docs table (or vice versa)"

    def project_check(
        self, modules: list[ModuleSource], ctx: Any
    ) -> Iterator[Finding]:
        from .context import env_vars_in_source

        documented = ctx.documented_env_vars
        used: dict[str, tuple[str, int]] = {}
        for module in modules:
            vars_here = env_vars_in_source(module.text, module.tree)
            for var, line in vars_here.items():
                used.setdefault(var, (module.path, line))
        for var in sorted(used):
            if var not in documented:
                path, line = used[var]
                yield Finding(
                    self.id,
                    self.severity,
                    path,
                    line,
                    0,
                    f"env var {var} is read here but has no row in the "
                    f"{ctx.env_doc_path} reference table — document it "
                    f"(or remove the dead knob)",
                    var,
                )
        # The reverse direction (documented but read nowhere) is only
        # meaningful over the full scan set; linting a subset would call
        # every table row stale. Proxy for "full scan": the faults
        # module is among the scanned files.
        if not any(m.path == ctx.faults_path for m in modules):
            return
        all_used = set(used) | set(ctx.extra_env_vars)
        for var in sorted(documented):
            if var not in all_used:
                yield Finding(
                    self.id,
                    self.severity,
                    ctx.env_doc_path,
                    documented[var],
                    0,
                    f"env var {var} is documented in the reference table "
                    f"but read by no scanned code (fluxmpi_tpu/, scripts/, "
                    f"bench.py) — delete the stale row or restore the "
                    f"knob",
                    f"unread:{var}",
                )


# ---------------------------------------------------------------------------
# Rule 7: jax-compat-drift
# ---------------------------------------------------------------------------


class JaxCompatDrift(Rule):
    """The version-compat seam contract (parallel/_compat.py): jax APIs
    whose spelling drifted across the jax versions this repo spans are
    wrapped ONCE, in ``fluxmpi_tpu/parallel/_compat.py`` — everything
    else imports the wrapper. A second try/except copy of the same
    probe is exactly how the kernel plane went dark for three API
    renames (ISSUE 19): each module's private fallback rotted at a
    different rate.

    Flagged anywhere outside the seam:

    1. ``lax.axis_size`` / ``jax.lax.axis_size`` attribute use (absent
       on older jax) — use ``_compat.axis_size(name)``;
    2. old pallas compiler-params spellings — any ``*CompilerParams``
       construction (``pltpu.CompilerParams`` / ``TPUCompilerParams``)
       — use ``_compat.pallas_tpu_compiler_params(...)``;
    3. a raw ``shard_map(...)`` call carrying the drifted validation
       keyword (``check_vma=`` new spelling / ``check_rep=`` old) — use
       ``_compat.shard_map_unchecked(...)`` (or plain
       ``_compat.shard_map`` without the keyword).
    """

    id = "jax-compat-drift"
    severity = "error"
    description = "drifted jax API spelled directly instead of via parallel/_compat"

    _ALLOWED = ("fluxmpi_tpu/parallel/_compat.py",)

    def check(self, module: ModuleSource, ctx: Any) -> Iterator[Finding]:
        if module.path in self._ALLOWED:
            return
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Attribute) and node.attr == "axis_size":
                root = value_root(node)
                if root in ("jax", "lax"):
                    yield self.finding(
                        module.path,
                        node,
                        "jax.lax.axis_size drifted across jax versions "
                        "(absent on older releases) — import axis_size "
                        "from fluxmpi_tpu.parallel._compat, the one "
                        "version probe",
                        "axis_size",
                    )
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                for alias in node.names:
                    if alias.name == "axis_size" and mod.endswith("lax"):
                        yield self.finding(
                            module.path,
                            node,
                            "importing axis_size from jax.lax drifts "
                            "across jax versions — import it from "
                            "fluxmpi_tpu.parallel._compat instead",
                            "axis_size",
                        )
                    elif alias.name.endswith("CompilerParams"):
                        yield self.finding(
                            module.path,
                            node,
                            f"pallas {alias.name} was renamed across jax "
                            f"versions — build compiler params via "
                            f"fluxmpi_tpu.parallel._compat."
                            f"pallas_tpu_compiler_params(...)",
                            "compiler_params",
                        )
            elif isinstance(node, ast.Call):
                name = terminal_name(node.func)
                if name is None:
                    continue
                if name.endswith("CompilerParams"):
                    yield self.finding(
                        module.path,
                        node,
                        f"pallas {name} was renamed across jax versions "
                        f"(CompilerParams ↔ TPUCompilerParams) — build "
                        f"compiler params via fluxmpi_tpu.parallel."
                        f"_compat.pallas_tpu_compiler_params(...)",
                        "compiler_params",
                    )
                elif name == "shard_map":
                    for kw in node.keywords:
                        if kw.arg in ("check_vma", "check_rep"):
                            yield self.finding(
                                module.path,
                                kw.value,
                                f"shard_map {kw.arg}= drifted across jax "
                                f"versions (check_rep ↔ check_vma) — call "
                                f"fluxmpi_tpu.parallel._compat."
                                f"shard_map_unchecked(...), which owns the "
                                f"keyword probe",
                                f"shard_map:{kw.arg}",
                            )


def default_rules() -> list[Rule]:
    return [
        SpmdDivergentCollective(),
        UnguardedHotPathInstrumentation(),
        UnknownMetricName(),
        UnregisteredFaultSite(),
        HandBuiltMesh(),
        UndocumentedEnvVar(),
        JaxCompatDrift(),
    ]
