"""fluxlint core: findings, suppressions, baseline, runner, reports.

The framework half of the repo's AST-based invariant checker (the rules
themselves live in :mod:`.rules`; the control-flow machinery they share
in :mod:`.flow`; repo-level knowledge — metric schema, fault-site
registry, env-var docs table — in :mod:`.context`). Deliberately pure
stdlib and import-safe without jax: ``scripts/fluxlint.py`` loads this
package standalone so a lint run never boots a backend.

Vocabulary:

- A **rule** has an ``id`` (the name used in suppressions and baseline
  entries), a ``severity`` (``error``/``warning`` — both fail the lint;
  the split is report metadata), and a ``check(module, ctx)`` generator
  over findings. File-scoped rules run per parsed module;
  project-scoped rules (``project_check(modules, ctx)``) run once over
  the whole scanned set (cross-file invariants: env-var table symmetry,
  fault-site test coverage).
- A **finding** carries a stable ``key`` besides its line/col: the
  thing that is wrong (a metric name, an env var, ``function:callee``),
  not where it currently sits. Baseline entries match on
  ``(rule, path, key)`` so a grandfathered finding survives unrelated
  line churn but dies with the code that caused it.
- An inline ``# fluxlint: disable=rule-a,rule-b`` comment suppresses
  those rules on its line (trailing or own-line form; an own-line
  comment suppresses the next statement line).
- The **baseline** file (``.fluxlint-baseline.json``) grandfathers
  findings; every entry must carry a non-empty ``justification`` and
  must still match a live finding — an unjustified or stale entry is
  itself a finding, so the baseline cannot rot silently.
"""

from __future__ import annotations

import ast
import json
import re
from typing import Any, Callable, Iterable, Iterator

BASELINE_BASENAME = ".fluxlint-baseline.json"

JSON_SCHEMA = "fluxmpi_tpu.fluxlint/v1"

_SUPPRESS_RE = re.compile(r"#\s*fluxlint:\s*disable=([A-Za-z0-9_,\- ]+)")


def _comment_tokens(text: str) -> list[tuple[int, int, str]]:
    """(line, col, comment-text) for every COMMENT token. Tokenization
    of a file that already ast-parsed can still hit edge cases; degrade
    to no suppressions rather than crash the lint."""
    import io
    import tokenize

    out: list[tuple[int, int, str]] = []
    try:
        for tok in tokenize.generate_tokens(io.StringIO(text).readline):
            if tok.type == tokenize.COMMENT:
                out.append((tok.start[0], tok.start[1], tok.string))
    except (tokenize.TokenError, IndentationError):  # pragma: no cover
        return []
    return out


class Finding:
    """One lint finding. ``key`` is the stable identity used by the
    baseline (see module docstring); ``line``/``col`` are 1-based /
    0-based like CPython's AST."""

    __slots__ = ("rule", "severity", "path", "line", "col", "message", "key")

    def __init__(
        self,
        rule: str,
        severity: str,
        path: str,
        line: int,
        col: int,
        message: str,
        key: str,
    ):
        self.rule = rule
        self.severity = severity
        self.path = path
        self.line = line
        self.col = col
        self.message = message
        self.key = key

    def to_json(self) -> dict[str, Any]:
        return {
            "rule": self.rule,
            "severity": self.severity,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "key": self.key,
        }

    def __str__(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: "
            f"{self.severity} [{self.rule}] {self.message}"
        )

    __repr__ = __str__


class ModuleSource:
    """A parsed source file plus its per-line suppression map."""

    def __init__(self, path: str, text: str):
        self.path = path
        self.text = text
        self.tree = ast.parse(text)
        # line -> set of rule ids disabled there. Directives are read
        # from COMMENT tokens only (a string literal quoting the
        # directive must not disable anything). An own-line comment
        # covers the next code line, skipping blank and further comment
        # lines — so a directive may sit above its justification
        # comment, which sits above the statement.
        self.suppressions: dict[int, set[str]] = {}
        lines = text.splitlines()
        for lineno, col, comment in _comment_tokens(text):
            m = _SUPPRESS_RE.search(comment)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            self.suppressions.setdefault(lineno, set()).update(rules)
            own_line = lines[lineno - 1][:col].strip() == ""
            if own_line:
                for j in range(lineno + 1, len(lines) + 1):
                    stripped = lines[j - 1].strip()
                    if not stripped or stripped.startswith("#"):
                        continue
                    self.suppressions.setdefault(j, set()).update(rules)
                    break

    def suppressed(self, line: int, rule_id: str) -> bool:
        return rule_id in self.suppressions.get(line, ())


class Rule:
    """Base rule. Subclasses set ``id``/``severity``/``description`` and
    implement :meth:`check` (file-scoped) and/or :meth:`project_check`
    (whole-scan-scoped; default: nothing)."""

    id: str = ""
    severity: str = "error"
    description: str = ""

    def check(self, module: ModuleSource, ctx: Any) -> Iterator[Finding]:
        return iter(())

    def project_check(
        self, modules: list[ModuleSource], ctx: Any
    ) -> Iterator[Finding]:
        return iter(())

    def finding(
        self, module_path: str, node: ast.AST | None, message: str, key: str
    ) -> Finding:
        line = getattr(node, "lineno", 0) if node is not None else 0
        col = getattr(node, "col_offset", 0) if node is not None else 0
        return Finding(
            self.id, self.severity, module_path, line, col, message, key
        )


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------


class Baseline:
    """Checked-in grandfather list. ``entries`` is a list of
    ``{"rule", "path", "key", "justification"}`` objects; matching and
    hygiene rules are in the module docstring."""

    def __init__(self, entries: list[dict[str, Any]], path: str = ""):
        self.entries = entries
        self.path = path

    @classmethod
    def load(cls, path: str) -> "Baseline":
        try:
            with open(path, encoding="utf-8") as f:
                data = json.load(f)
        except FileNotFoundError:
            return cls([], path)
        entries = data.get("entries", []) if isinstance(data, dict) else []
        return cls([e for e in entries if isinstance(e, dict)], path)

    def _matches(self, finding: Finding) -> dict[str, Any] | None:
        for entry in self.entries:
            if (
                entry.get("rule") == finding.rule
                and entry.get("path") == finding.path
                and entry.get("key") == finding.key
            ):
                return entry
        return None

    def apply(
        self, findings: list[Finding]
    ) -> tuple[list[Finding], list[Finding], list[Finding]]:
        """Split ``findings`` into (active, baselined) and append the
        baseline's own hygiene findings (stale entry, missing
        justification) to the active list via the third return."""
        active: list[Finding] = []
        baselined: list[Finding] = []
        used: set[int] = set()
        for f in findings:
            entry = self._matches(f)
            if entry is None:
                active.append(f)
                continue
            used.add(id(entry))
            if not str(entry.get("justification", "")).strip():
                active.append(
                    Finding(
                        "fluxlint-baseline",
                        "error",
                        f.path,
                        f.line,
                        f.col,
                        f"baseline entry for [{f.rule}] {f.key!r} has no "
                        f"justification — every grandfathered finding "
                        f"must say why it is kept",
                        f"unjustified:{f.rule}:{f.key}",
                    )
                )
            else:
                baselined.append(f)
        hygiene: list[Finding] = []
        for entry in self.entries:
            if id(entry) in used:
                continue
            hygiene.append(
                Finding(
                    "fluxlint-baseline",
                    "error",
                    str(entry.get("path", self.path or BASELINE_BASENAME)),
                    0,
                    0,
                    f"stale baseline entry: [{entry.get('rule')}] "
                    f"{entry.get('key')!r} no longer matches any finding — "
                    f"delete it",
                    f"stale:{entry.get('rule')}:{entry.get('key')}",
                )
            )
        return active, baselined, hygiene


# ---------------------------------------------------------------------------
# Runner
# ---------------------------------------------------------------------------


class Report:
    def __init__(self) -> None:
        self.findings: list[Finding] = []  # active (fail the lint)
        self.baselined: list[Finding] = []
        self.suppressed: int = 0
        self.files: int = 0
        self.unreadable: list[str] = []  # "path: error" strings

    @property
    def exit_code(self) -> int:
        """0 clean / 1 findings / 2 unreadable input — the
        ``check_metrics_schema.py`` exit-code convention."""
        if self.unreadable:
            return 2
        return 1 if self.findings else 0

    def to_json(self) -> dict[str, Any]:
        return {
            "schema": JSON_SCHEMA,
            "files": self.files,
            "findings": [f.to_json() for f in self.findings],
            "baselined": [f.to_json() for f in self.baselined],
            "suppressed": self.suppressed,
            "unreadable": list(self.unreadable),
            "exit_code": self.exit_code,
        }

    def text(self) -> str:
        out = [str(f) for f in self.findings]
        out.extend(f"unreadable: {u}" for u in self.unreadable)
        out.append(
            f"fluxlint: {self.files} file(s), {len(self.findings)} "
            f"finding(s), {len(self.baselined)} baselined, "
            f"{self.suppressed} suppressed"
        )
        return "\n".join(out)


def lint_modules(
    modules: list[ModuleSource],
    rules: Iterable[Rule],
    ctx: Any,
    baseline: Baseline | None = None,
) -> Report:
    """Run ``rules`` over parsed ``modules``; apply suppressions, then
    the baseline. The shared core of the CLI and the in-process tests."""
    report = Report()
    report.files = len(modules)
    raw: list[Finding] = []
    rules = list(rules)
    for rule in rules:
        for module in modules:
            for f in rule.check(module, ctx):
                if module.suppressed(f.line, f.rule):
                    report.suppressed += 1
                else:
                    raw.append(f)
        for f in rule.project_check(modules, ctx):
            by_path = {m.path: m for m in modules}
            m = by_path.get(f.path)
            if m is not None and m.suppressed(f.line, f.rule):
                report.suppressed += 1
            else:
                raw.append(f)
    raw.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    if baseline is None:
        report.findings = raw
        return report
    active, baselined, hygiene = baseline.apply(raw)
    report.findings = active + hygiene
    report.baselined = baselined
    return report


def parse_files(
    paths: Iterable[str],
    repo_root: str,
    read: Callable[[str], str],
) -> tuple[list[ModuleSource], list[str]]:
    """Parse ``paths`` (absolute) into modules keyed by repo-relative
    posix paths; unreadable/unparsable files land in the error list."""
    import os

    modules: list[ModuleSource] = []
    errors: list[str] = []
    for path in paths:
        rel = os.path.relpath(path, repo_root).replace(os.sep, "/")
        try:
            modules.append(ModuleSource(rel, read(path)))
        except (OSError, SyntaxError, ValueError) as exc:
            errors.append(f"{rel}: {exc}")
    return modules, errors
