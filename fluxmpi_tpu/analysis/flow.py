"""Control-flow helpers for the SPMD / hot-path rules.

Three analyses, all deliberately conservative (lint-grade, not
verifier-grade — when unsure, classify as "unrelated" so the rules stay
quiet rather than noisy):

- **Rank conditionality** (:func:`rank_condition`): does an ``if`` test
  depend on *which process* is running — ``jax.process_index()``,
  ``local_rank()``, ``is_distributed()``, ``is_lead()`` — directly or
  through a local bool (``lead = jax.process_index() == 0``)? World-size
  tests (``process_count()``) are NOT rank-conditional: every process
  evaluates them identically, so they cannot diverge the collective
  sequence.
- **Guard classification** (:func:`classify_guard`): is a condition the
  instrumentation fast-guard — a call ending in ``_instrumentation_on``,
  an ``.enabled`` attribute read, or a local bool resolved from one
  (``instrumented = _instrumentation_on()``, ``gp_on = gp.enabled``)?
  Conditions classify as GUARD_ON (true ⇒ instrumentation enabled),
  GUARD_OFF (true ⇒ disabled), or OTHER.
- **Termination** (:func:`terminates`): does a block never fall through
  (trailing return/raise/continue/break, an if whose branches both
  terminate, or a ``while True`` with no break)? Used for the early-exit
  guard idiom (``if not instrumented: return fast_path()``) and for the
  divergent-early-exit half of the SPMD rule.
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator

GUARD_ON = "on"
GUARD_OFF = "off"
OTHER = "other"

# Terminal callable names whose result depends on the calling process's
# rank. process_count / device_count are absent on purpose (world-size
# conditions are SPMD-consistent).
RANK_FUNCS = frozenset(
    {
        "process_index",
        "process_index_or_zero",
        "local_rank",
        "is_distributed",
        "is_lead",
        "_is_lead",
    }
)

# Terminal callable names of the instrumentation fast-guard family.
GUARD_FUNCS = frozenset({"_instrumentation_on", "instrumentation_on"})


def terminal_name(func: ast.expr) -> str | None:
    """The rightmost name of a call target: ``f`` for ``f(...)``,
    ``meth`` for ``a.b.meth(...)``; None for anything fancier."""
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def value_root(func: ast.expr) -> str | None:
    """The leftmost name of an attribute chain: ``comm`` for
    ``comm.allreduce``; None for bare names / computed values."""
    node = func
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def walk_no_nested_functions(node: ast.AST) -> Iterator[ast.AST]:
    """ast.walk that does not descend into nested function/class
    definitions (they get their own analysis pass)."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(
                child,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda),
            ):
                continue
            stack.append(child)


# ---------------------------------------------------------------------------
# Rank conditionality
# ---------------------------------------------------------------------------


def _mentions_rank(expr: ast.expr, rank_names: set[str]) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            name = terminal_name(node.func)
            if name in RANK_FUNCS:
                return True
        elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            if node.id in rank_names:
                return True
    return False


def rank_derived_names(fn: ast.AST) -> set[str]:
    """Local names assigned from a rank-dependent expression
    (``lead = jax.process_index() == 0``), one transitive pass."""
    names: set[str] = set()
    for _ in range(2):  # two passes: catch one level of chaining
        for node in walk_no_nested_functions(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = node.targets[0]
                if isinstance(target, ast.Name) and _mentions_rank(
                    node.value, names
                ):
                    names.add(target.id)
    return names


def rank_condition(test: ast.expr, rank_names: set[str]) -> bool:
    """True when an ``if`` test depends on the process rank."""
    return _mentions_rank(test, rank_names)


# ---------------------------------------------------------------------------
# Guard classification
# ---------------------------------------------------------------------------


def _is_guard_expr(expr: ast.expr, guard_names: dict[str, str]) -> bool:
    """A positive instrumentation-guard expression (no negation).
    ``guard_names`` maps derived local names to their polarity; only
    GUARD_ON names count here — an ``off = not reg.enabled`` local is
    truthy precisely when instrumentation is DISABLED."""
    if isinstance(expr, ast.Call):
        name = terminal_name(expr.func)
        if name in GUARD_FUNCS:
            return True
        return False
    if isinstance(expr, ast.Attribute) and expr.attr == "enabled":
        return True
    if isinstance(expr, ast.Name):
        return guard_names.get(expr.id) == GUARD_ON
    return False


def _is_none_const(expr: ast.expr) -> bool:
    return isinstance(expr, ast.Constant) and expr.value is None


def guard_derived_names(fn: ast.AST) -> dict[str, str]:
    """Local names resolved from guard expressions, with POLARITY:
    ``instrumented = _instrumentation_on()`` / ``gp_on = gp.enabled`` →
    GUARD_ON (truthy ⇒ instrumentation enabled);
    ``off = not reg.enabled`` → GUARD_OFF (truthy ⇒ disabled);
    ``depth = g.gauge(...) if reg.enabled else None`` → GUARD_ON (the
    value is non-None exactly when enabled). Two passes catch one level
    of chaining."""
    names: dict[str, str] = {}
    for _ in range(2):
        for node in walk_no_nested_functions(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = node.targets[0]
                if not isinstance(target, ast.Name):
                    continue
                cls = classify_guard(node.value, names)
                if cls in (GUARD_ON, GUARD_OFF):
                    names[target.id] = cls
    return names


def classify_guard(test: ast.expr, guard_names: dict[str, str]) -> str:
    """GUARD_ON / GUARD_OFF / OTHER for an ``if``/``while`` test, an
    ``IfExp`` condition, or an assigned value whose truthiness tracks
    the guard (semantics in the module docstring)."""
    if _is_guard_expr(test, guard_names):
        return GUARD_ON
    if isinstance(test, ast.Name):
        return guard_names.get(test.id, OTHER)
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        inner = classify_guard(test.operand, guard_names)
        if inner == GUARD_ON:
            return GUARD_OFF
        if inner == GUARD_OFF:
            return GUARD_ON
        return OTHER
    if isinstance(test, ast.IfExp):
        # `x if guard else None`: non-None (truthy-ish) exactly when the
        # guard is — the resolved-handle idiom. Symmetric for OFF.
        tcls = classify_guard(test.test, guard_names)
        if tcls == GUARD_ON and _is_none_const(test.orelse):
            return GUARD_ON
        if tcls == GUARD_OFF and _is_none_const(test.orelse):
            return GUARD_OFF
        if tcls == GUARD_ON and _is_none_const(test.body):
            return GUARD_OFF
        if tcls == GUARD_OFF and _is_none_const(test.body):
            return GUARD_ON
        return OTHER
    if isinstance(test, ast.BoolOp):
        parts = [classify_guard(v, guard_names) for v in test.values]
        if isinstance(test.op, ast.And):
            # `guard and x` runs only with the guard on; `not g and not h`
            # only with both off.
            if GUARD_ON in parts:
                return GUARD_ON
            if parts and all(p == GUARD_OFF for p in parts):
                return GUARD_OFF
            if GUARD_OFF in parts:
                return GUARD_OFF
            return OTHER
        # Or: truth implies nothing unless every arm agrees.
        if parts and all(p == parts[0] for p in parts):
            return parts[0]
        return OTHER
    if isinstance(test, ast.Compare) and len(test.ops) == 1:
        left_cls = OTHER
        if _is_guard_expr(test.left, guard_names):
            left_cls = GUARD_ON
        elif isinstance(test.left, ast.Name):
            left_cls = guard_names.get(test.left.id, OTHER)
        if left_cls != OTHER and _is_none_const(test.comparators[0]):
            flip = left_cls == GUARD_OFF
            if isinstance(test.ops[0], ast.IsNot):
                return GUARD_OFF if flip else GUARD_ON
            if isinstance(test.ops[0], ast.Is):
                return GUARD_ON if flip else GUARD_OFF
    return OTHER


# ---------------------------------------------------------------------------
# Termination
# ---------------------------------------------------------------------------


def _while_true_no_break(node: ast.While) -> bool:
    if not (isinstance(node.test, ast.Constant) and node.test.value is True):
        return False
    for child in walk_no_nested_functions(node):
        if child is node:
            continue
        if isinstance(child, (ast.While, ast.For)):
            # breaks inside an inner loop bind to that loop — prune by
            # not descending (walk_no_nested_functions cannot prune
            # mid-walk, so re-walk with an explicit check)
            continue
        if isinstance(child, ast.Break) and _innermost_loop_is(node, child):
            return False
    return True


def _innermost_loop_is(loop: ast.AST, brk: ast.Break) -> bool:
    # Structural check: is `brk` inside `loop` but not inside a nested
    # loop of it? Walk loop's body tracking loop nesting.
    def scan(stmts: Iterable[ast.stmt], depth: int) -> bool | None:
        for stmt in stmts:
            if stmt is brk:
                return depth == 0
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if sub:
                    d = depth + (
                        1 if isinstance(stmt, (ast.While, ast.For)) else 0
                    )
                    found = scan(sub, d)
                    if found is not None:
                        return found
            handlers = getattr(stmt, "handlers", None)
            if handlers:
                for h in handlers:
                    found = scan(h.body, depth)
                    if found is not None:
                        return found
        return None

    return bool(scan(loop.body, 0))


def terminates(block: list[ast.stmt]) -> bool:
    """Does this block never fall through to the statement after it?"""
    if not block:
        return False
    last = block[-1]
    if isinstance(last, (ast.Return, ast.Raise, ast.Continue, ast.Break)):
        return True
    if isinstance(last, ast.If):
        return terminates(last.body) and terminates(last.orelse)
    if isinstance(last, ast.While):
        return _while_true_no_break(last)
    if isinstance(last, ast.Try):
        final_ok = terminates(last.finalbody) if last.finalbody else False
        if final_ok:
            return True
        body_ok = terminates(last.body)
        handlers_ok = all(terminates(h.body) for h in last.handlers)
        return body_ok and handlers_ok and bool(last.handlers)
    if isinstance(last, ast.With):
        return terminates(last.body)
    return False
