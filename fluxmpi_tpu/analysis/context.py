"""Project knowledge the fluxlint rules check against.

Every registry the rules consult is **single-sourced from the artifact
that owns it** — never a copied list in this package:

- metric names / closed namespaces / trace-event constants come from
  ``fluxmpi_tpu/telemetry/schema.py``, loaded **by file path** (the
  module is deliberately stdlib-only, so this works without jax — the
  same trick ``scripts/check_metrics_schema.py`` uses, via the shared
  :func:`load_schema_module`);
- fault sites come from the ``KNOWN_SITES`` literal in
  ``fluxmpi_tpu/faults.py``, extracted from its AST (importing faults.py
  would pull in the telemetry package and, transitively, numpy — the
  literal IS the registry, so reading it statically keeps the lint
  backend-free);
- documented env vars come from the reference-table rows of
  ``docs/observability.md`` (lines starting with ``|`` whose cells name
  a backticked ``FLUXMPI_TPU_*`` variable);
- the tests corpus is the concatenated text of ``tests/*.py`` (fault-
  site test coverage is a lint-time grep, per the rule contract).

Tests build synthetic contexts directly instead of loading a repo.
"""

from __future__ import annotations

import ast
import importlib.util
import os
import re
from typing import Any, Iterable

ENV_VAR_RE = re.compile(r"\bFLUXMPI_TPU_[A-Z0-9_]+\b")

_DOC_ROW_RE = re.compile(r"^\s*\|")

SCHEMA_RELPATH = os.path.join("fluxmpi_tpu", "telemetry", "schema.py")
FAULTS_RELPATH = os.path.join("fluxmpi_tpu", "faults.py")
CONFIG_RELPATH = os.path.join("fluxmpi_tpu", "config.py")
ENV_DOC_RELPATH = os.path.join("docs", "observability.md")

# Files outside the default scan set that legitimately read FLUXMPI_TPU_*
# env vars; the undocumented-env-var rule's reverse check (documented but
# read nowhere) scans these too, so a bench-only knob doesn't look dead
# when only `fluxmpi_tpu/ scripts/` are linted.
EXTRA_ENV_ROOTS = ("bench.py",)


def load_schema_module(repo_root: str) -> Any:
    """Load ``fluxmpi_tpu/telemetry/schema.py`` by file path — no package
    import, no jax. Shared by fluxlint and check_metrics_schema.py (one
    loader, one source of schema truth)."""
    path = os.path.join(repo_root, SCHEMA_RELPATH)
    spec = importlib.util.spec_from_file_location("_fluxmpi_schema", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def known_fault_sites(repo_root: str) -> frozenset[str]:
    """The ``KNOWN_SITES`` literal of ``fluxmpi_tpu/faults.py``,
    extracted statically (see module docstring)."""
    path = os.path.join(repo_root, FAULTS_RELPATH)
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read())
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not (isinstance(target, ast.Name) and target.id == "KNOWN_SITES"):
            continue
        value = node.value
        if isinstance(value, ast.Call) and value.args:
            value = value.args[0]  # frozenset({...})
        if isinstance(value, (ast.Set, ast.Tuple, ast.List)):
            elems = [
                e.value
                for e in value.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            ]
            return frozenset(elems)
    raise ValueError(
        f"no KNOWN_SITES literal found in {path} — the fault-site "
        f"registry the unregistered-fault-site rule checks against"
    )


def axis_name_literals(repo_root: str) -> frozenset[str]:
    """The default mesh-axis names from ``fluxmpi_tpu/config.py``'s
    ``_DEFAULTS`` literal (the ``*_axis_name`` rows), extracted
    statically — the registry the hand-built-mesh rule checks axis-name
    literals against. Single-sourced: a renamed default axis updates the
    lint with no copy to drift."""
    path = os.path.join(repo_root, CONFIG_RELPATH)
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read())
    names: set[str] = set()
    for node in ast.walk(tree):
        # `_DEFAULTS: dict[...] = {...}` is an AnnAssign; a bare
        # `_DEFAULTS = {...}` would be an Assign — accept both.
        if isinstance(node, ast.AnnAssign):
            target = node.target
        elif isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
        else:
            continue
        if not (isinstance(target, ast.Name) and target.id == "_DEFAULTS"):
            continue
        if isinstance(node.value, ast.Dict):
            for key, value in zip(node.value.keys, node.value.values):
                if (
                    isinstance(key, ast.Constant)
                    and isinstance(key.value, str)
                    and key.value.endswith("_axis_name")
                    and isinstance(value, ast.Constant)
                    and isinstance(value.value, str)
                ):
                    names.add(value.value)
    if not names:
        raise ValueError(
            f"no *_axis_name defaults found in {path} — the axis-name "
            f"registry the hand-built-mesh rule checks against"
        )
    return frozenset(names)


def documented_env_vars(repo_root: str) -> dict[str, int]:
    """Env vars named in the docs reference table → line number of the
    row. Only table rows count (prose mentions are documentation *about*
    a variable, not its reference entry)."""
    path = os.path.join(repo_root, ENV_DOC_RELPATH)
    out: dict[str, int] = {}
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f, 1):
            if not _DOC_ROW_RE.match(line):
                continue
            for var in ENV_VAR_RE.findall(line):
                out.setdefault(var, i)
    return out


def tests_corpus(repo_root: str) -> str:
    """Concatenated text of ``tests/*.py`` for coverage greps."""
    tests_dir = os.path.join(repo_root, "tests")
    chunks: list[str] = []
    try:
        names = sorted(os.listdir(tests_dir))
    except FileNotFoundError:
        return ""
    for name in names:
        if not name.endswith(".py"):
            continue
        try:
            with open(
                os.path.join(tests_dir, name), encoding="utf-8"
            ) as f:
                chunks.append(f.read())
        except OSError:
            continue
    return "\n".join(chunks)


def env_vars_in_source(
    text: str, tree: ast.AST | None = None
) -> dict[str, int]:
    """``FLUXMPI_TPU_*`` string literals in python source → first line,
    docstrings excluded (a variable mentioned only in prose is not a
    read). Pass an already-parsed ``tree`` to skip the re-parse; falls
    back to a raw-text regex when the file doesn't parse."""
    if tree is None:
        try:
            tree = ast.parse(text)
        except (SyntaxError, ValueError):
            out: dict[str, int] = {}
            for i, line in enumerate(text.splitlines(), 1):
                for var in ENV_VAR_RE.findall(line):
                    out.setdefault(var, i)
            return out
    doc_consts: set[int] = set()
    for node in ast.walk(tree):
        if isinstance(
            node,
            (ast.Module, ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
        ):
            body = node.body
            if (
                body
                and isinstance(body[0], ast.Expr)
                and isinstance(body[0].value, ast.Constant)
                and isinstance(body[0].value.value, str)
            ):
                doc_consts.add(id(body[0].value))
    out = {}
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and id(node) not in doc_consts
        ):
            for var in ENV_VAR_RE.findall(node.value):
                out.setdefault(var, node.lineno)
    return out


class ProjectContext:
    """Everything the rules need to know about the repo. Built once per
    lint run by :meth:`load`; tests construct instances directly with
    synthetic registries."""

    def __init__(
        self,
        *,
        known_metric_names: frozenset[str] = frozenset(),
        closed_namespaces: tuple[str, ...] = (),
        preemption_event: str = "train.preemption",
        anomaly_event_prefix: str = "anomaly.",
        known_fault_sites: frozenset[str] = frozenset(),
        documented_env_vars: dict[str, int] | None = None,
        extra_env_vars: Iterable[str] = (),
        tests_corpus: str = "",
        env_doc_path: str = "docs/observability.md",
        faults_path: str = "fluxmpi_tpu/faults.py",
        axis_name_literals: frozenset[str] = frozenset(),
    ):
        self.known_metric_names = known_metric_names
        self.closed_namespaces = closed_namespaces
        self.preemption_event = preemption_event
        self.anomaly_event_prefix = anomaly_event_prefix
        self.known_fault_sites = known_fault_sites
        self.documented_env_vars = documented_env_vars or {}
        # Env vars read by files outside the scan set (bench.py).
        self.extra_env_vars = frozenset(extra_env_vars)
        self.tests_corpus = tests_corpus
        self.env_doc_path = env_doc_path
        self.faults_path = faults_path
        self.axis_name_literals = axis_name_literals

    @classmethod
    def load(cls, repo_root: str) -> "ProjectContext":
        schema = load_schema_module(repo_root)
        extra: set[str] = set()
        for rel in EXTRA_ENV_ROOTS:
            try:
                with open(
                    os.path.join(repo_root, rel), encoding="utf-8"
                ) as f:
                    extra.update(env_vars_in_source(f.read()))
            except OSError:
                continue
        return cls(
            known_metric_names=frozenset(schema.KNOWN_METRIC_NAMES),
            closed_namespaces=tuple(schema._CLOSED_NAMESPACES),
            preemption_event=schema.PREEMPTION_EVENT,
            anomaly_event_prefix=schema.ANOMALY_EVENT_PREFIX,
            known_fault_sites=known_fault_sites(repo_root),
            documented_env_vars=documented_env_vars(repo_root),
            extra_env_vars=extra,
            tests_corpus=tests_corpus(repo_root),
            axis_name_literals=axis_name_literals(repo_root),
        )
