"""Runtime bring-up, world identity, and the global device mesh.

TPU-native redesign of the reference's L3 runtime (reference: src/common.jl).
The reference world is MPI: ``mpiexecjl`` spawns one OS process per rank, each
rank binds one GPU round-robin (src/common.jl:16-45), and every collective
runs over ``MPI.COMM_WORLD``. The TPU world is SPMD over a named device mesh:
``init()`` optionally joins a multi-host pod slice
(``jax.distributed.initialize``), then builds a :class:`jax.sharding.Mesh`
over all global devices. XLA owns device binding — there is no analogue of
``CUDA.device!`` because every collective is compiled against the mesh.

Identity mapping (the reference collapses process == rank == GPU; a TPU
controller process drives several chips, so the two notions split):

- :func:`total_workers` — the number of data-parallel workers, i.e. global
  device count (reference: ``MPI.Comm_size``, src/common.jl:64-69; one worker
  held one GPU there, one worker is one TPU chip here).
- :func:`local_rank` — the rank of this controller process
  (reference: ``MPI.Comm_rank``, src/common.jl:52-57). Use
  :func:`process_count` / :func:`local_device_count` for the full picture.

Both queries raise ``FluxMPINotInitializedError`` before ``init()``
(reference: src/common.jl:53,65) and are safe inside differentiated code: they
return Python ints, invisible to tracing — the analogue of the reference's
``@non_differentiable`` marks (src/common.jl:57,69).
"""

from __future__ import annotations

import os
import signal
import warnings
from typing import Any, Sequence

import numpy as np

import jax
from jax.sharding import Mesh

from . import config
from .errors import FluxMPINotInitializedError, TopologyMismatchError

__all__ = [
    "init",
    "is_initialized",
    "Initialized",
    "enable_compile_cache",
    "shutdown",
    "local_rank",
    "total_workers",
    "process_index",
    "process_count",
    "device_count",
    "local_device_count",
    "global_mesh",
    "global_plan",
    "auto_parallel",
    "dp_axis_name",
    "preemption_requested",
    "request_preemption",
    "clear_preemption",
    "install_preemption_handlers",
    "uninstall_preemption_handlers",
    "preemption_handlers_installed",
]


class _RuntimeState:
    initialized: bool = False
    mesh: Mesh | None = None
    plan: Any = None  # the ResolvedPlan behind init(parallel=), if any
    distributed: bool = False
    # init(parallel="auto") / FLUXMPI_TPU_PARALLEL=auto was requested:
    # the mesh starts as the dp default and the layout autotuner's
    # winner is installed over it via _install_autotuned_plan.
    auto_parallel: bool = False


_state = _RuntimeState()


# ---------------------------------------------------------------------------
# Preemption plane: SIGTERM/SIGINT → a flag the training loop polls.
#
# TPU preemption delivers SIGTERM with a grace window; the handler must be
# signal-safe, so — same rule as the watchdog's SIGUSR1 handler — it ONLY
# sets a plain flag (no locks, no I/O, no jax). `train_loop` polls
# `preemption_requested()` at dispatch boundaries, drains its in-flight
# window, writes an emergency checkpoint, and returns cleanly with
# ``summary["preempted"] = True`` (see docs/fault_tolerance.md).
# ---------------------------------------------------------------------------

_PREEMPTION_ENV = "FLUXMPI_TPU_PREEMPTION"

_SIGNALS_BY_NAME = {
    "term": (signal.SIGTERM,),
    "int": (signal.SIGINT,),
    "both": (signal.SIGTERM, signal.SIGINT),
}


class _PreemptionState:
    requested: bool = False
    signum: int | None = None


_preemption = _PreemptionState()
_prev_signal_handlers: dict[int, Any] = {}


def preemption_requested() -> bool:
    """Has a preemption signal (or :func:`request_preemption`) arrived?
    One attribute read — cheap enough to poll every dispatch."""
    return _preemption.requested


def request_preemption(signum: int | None = None) -> None:
    """Set the preemption flag programmatically (what the signal handler
    does; also the test hook — no real signal needed)."""
    _preemption.requested = True
    _preemption.signum = signum


def clear_preemption() -> None:
    """Reset the flag (a driver that handled one preemption and decided
    to continue, or test teardown)."""
    _preemption.requested = False
    _preemption.signum = None


def _on_preemption_signal(signum: int, frame: Any) -> None:
    # Runs between bytecodes on the main thread: only a flag write is
    # safe here (the watchdog signal-safety rule — a handler that took a
    # registry/IO lock could deadlock the loop it is trying to stop).
    _preemption.requested = True
    _preemption.signum = signum


def install_preemption_handlers(
    signals: Sequence[int] = (signal.SIGTERM, signal.SIGINT),
) -> None:
    """Install the flag-setting handler for ``signals`` (idempotent; the
    previous handlers are remembered for
    :func:`uninstall_preemption_handlers`). Must run on the main thread;
    elsewhere the install is skipped with a warning (the flag can still
    be set via :func:`request_preemption`)."""
    for sig in signals:
        if sig in _prev_signal_handlers:
            continue
        try:
            _prev_signal_handlers[sig] = signal.signal(
                sig, _on_preemption_signal
            )
        except (ValueError, OSError) as exc:  # non-main thread / platform
            warnings.warn(
                f"cannot install preemption handler for signal {sig}: "
                f"{exc}; preemption polling still works via "
                f"request_preemption()",
                stacklevel=2,
            )


def preemption_handlers_installed() -> bool:
    """Is the flag-setting signal handler currently installed? The
    install is SPMD-consistent (same ``init(preemption=)`` / env on
    every process), so multi-process ``train_loop`` gates its
    coordinated preemption poll on this and every process answers
    alike."""
    return bool(_prev_signal_handlers)


def uninstall_preemption_handlers() -> None:
    """Restore the pre-install signal handlers and clear the flag."""
    for sig, prev in list(_prev_signal_handlers.items()):
        try:
            signal.signal(sig, prev)
        except (ValueError, OSError):
            pass
        del _prev_signal_handlers[sig]
    clear_preemption()


def _configure_preemption(spec: Any = None) -> None:
    """Wire preemption handling from a one-value spec (mirror of
    ``telemetry.configure``): ``None`` reads ``FLUXMPI_TPU_PREEMPTION``
    (no-op when unset); ``True``/``"1"``/``"both"`` installs
    SIGTERM+SIGINT; ``"term"``/``"int"`` installs just that signal;
    ``False``/``"0"`` uninstalls."""
    if spec is None:
        spec = os.environ.get(_PREEMPTION_ENV)
        if spec is None or spec == "":
            return
    if spec is False or spec == "0":
        uninstall_preemption_handlers()
        return
    if spec is True or spec == "1":
        spec = "both"
    if not isinstance(spec, str) or spec not in _SIGNALS_BY_NAME:
        raise ValueError(
            f"preemption spec must be a bool or one of "
            f"{sorted(_SIGNALS_BY_NAME)}; got {spec!r}"
        )
    install_preemption_handlers(_SIGNALS_BY_NAME[spec])


# ---------------------------------------------------------------------------
# Persistent XLA compilation cache: fleet-scale cold start pays compile
# once (shared storage), not once per host — the AOT-lowered fused-window
# programs and every other jit land in it.
# ---------------------------------------------------------------------------

_COMPILE_CACHE_ENV = "FLUXMPI_TPU_COMPILE_CACHE"
_COMPILE_CACHE_DEFAULT_DIR = "/tmp/fluxmpi_tpu_xla_cache"


def enable_compile_cache(cache_dir: str | None = None) -> bool:
    """Point XLA's persistent compilation cache at ``cache_dir`` (default
    ``FLUXMPI_TPU_COMPILE_CACHE``, else ``/tmp/fluxmpi_tpu_xla_cache``)
    so repeat runs — and, on shared storage, every host of a fleet —
    skip the slow first compile. Returns True when enabled.

    TPU only: XLA:CPU persists AOT executables keyed too loosely — an
    entry compiled on a host with different CPU features loads anyway
    ("may SIGILL") and in practice kills device threads, wedging
    multi-device collective rendezvous. On other backends this is a
    no-op (with a warning when the cache was explicitly requested)."""
    import jax

    explicit = cache_dir is not None or bool(
        os.environ.get(_COMPILE_CACHE_ENV)
    )
    if cache_dir is None:
        cache_dir = (
            os.environ.get(_COMPILE_CACHE_ENV) or _COMPILE_CACHE_DEFAULT_DIR
        )
    if jax.default_backend() != "tpu":
        if explicit:
            warnings.warn(
                "persistent compile cache skipped: XLA:CPU persists AOT "
                "executables keyed too loosely across hosts (stale "
                "entries can SIGILL device threads); the cache is "
                "TPU-only",
                stacklevel=2,
            )
        return False
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # pragma: no cover - jax-version dependent
        return False
    return True


def _configure_compile_cache(spec: Any = None) -> None:
    """Wire the persistent compile cache from a one-value spec (mirror
    of ``telemetry.configure``): ``None`` reads
    ``FLUXMPI_TPU_COMPILE_CACHE`` (no-op when unset); a path string
    enables the cache there; ``True``/``"1"`` enables the default
    location; ``False``/``"0"`` is a no-op (the cache config is
    process-global jax state — there is nothing to detach)."""
    if spec is None:
        spec = os.environ.get(_COMPILE_CACHE_ENV)
        if spec is None or spec == "":
            return
    if spec is False or spec == "0":
        return
    if spec is True or spec == "1":
        enable_compile_cache()
        return
    if isinstance(spec, str):
        enable_compile_cache(spec)
        return
    raise ValueError(
        f"compile_cache spec must be a bool, '0'/'1', or a directory "
        f"path; got {spec!r}"
    )


def _same_rule_config(a: Any, b: Any) -> bool:
    """Do two ParallelConfigs declare the same partition-rule behavior?
    Tables compare by value, callables by identity (== on functions)."""
    try:
        same_rules = a.rules is b.rules or a.rules == b.rules
    except Exception:
        same_rules = False
    return (
        bool(same_rules)
        and a.strict == b.strict
        and a.fsdp_min_size == b.fsdp_min_size
    )


def _same_plan(parallel: Any, installed: Any) -> bool:
    """Is the ``parallel=`` argument of an idempotent ``init`` replay the
    layout already installed? True for the installed plan itself, its
    source config, an equivalent re-resolved plan, or a config declaring
    the same axis sizes/names AND rule behavior (rules/strict/
    fsdp_min_size — a replay changing the rule table must warn, not
    silently keep the old one) — replaying the same declaration must
    stay warning-free."""
    if installed is None:
        return False
    if parallel is installed or parallel is installed.config:
        return True
    sizes = getattr(parallel, "sizes", None)
    names = getattr(parallel, "axis_names", None)
    if not (isinstance(sizes, dict) and isinstance(names, dict)):
        return False
    cfg = installed.config
    other = getattr(parallel, "config", None)
    if other is not None:
        # A re-resolved ResolvedPlan: its sizes/axis_names are the
        # RESOLVED mesh-axes-only dicts — compare against the installed
        # plan's resolved layout, not the raw config (whose six-axis,
        # possibly -1 declaration can never dict-equal it).
        return (
            sizes == installed.sizes
            and names == installed.axis_names
            and _same_rule_config(other, cfg)
        )
    if not _same_rule_config(parallel, cfg):
        return False
    if sizes == cfg.sizes and names == cfg.axis_names:
        return True
    # Different declaration, possibly the same layout (dp=-1 vs dp=8):
    # resolve against the installed mesh's devices and compare the
    # resolved layouts.
    try:
        resolved = parallel.resolve(list(installed.mesh.devices.flat))
    except Exception:
        return False
    return (
        resolved.sizes == installed.sizes
        and resolved.axis_names == installed.axis_names
    )


def _should_init_distributed() -> bool:
    """Heuristic for joining a multi-host world at ``init()``.

    The reference always calls ``MPI.Init()`` because ``mpiexecjl`` created
    the world (src/common.jl:22). On TPU the world exists iff we run on a pod
    slice or the coordinator is configured explicitly.
    """
    if os.environ.get("JAX_COORDINATOR_ADDRESS") or os.environ.get(
        "COORDINATOR_ADDRESS"
    ):
        return True
    # Cloud TPU pod slice: multiple workers announced by the TPU VM runtime.
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    return len([h for h in hostnames.split(",") if h]) > 1


def init(
    *,
    devices: Sequence[jax.Device] | None = None,
    mesh_shape: dict[str, int] | None = None,
    parallel: Any = None,
    distributed: bool | None = None,
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    verbose: bool = False,
    telemetry: Any = None,
    trace: Any = None,
    watchdog: Any = None,
    preemption: Any = None,
    faults: Any = None,
    goodput: Any = None,
    anomaly: Any = None,
    model_stats: Any = None,
    compileplane: Any = None,
    memory: Any = None,
    profile: Any = None,
    compile_cache: Any = None,
    export: Any = None,
    serving: Any = None,
    request_log: Any = None,
    fleet: Any = None,
    resize: Any = None,
) -> Mesh:
    """Bring up the fluxmpi_tpu runtime. Idempotent.

    TPU-native analogue of ``FluxMPI.Init`` (reference: src/common.jl:16-45):

    - joins the multi-host world when on a pod slice (analogue of
      ``MPI.Init()`` joining the mpiexec world, src/common.jl:22);
    - builds the global device mesh (analogue of rank→GPU round-robin
      binding, src/common.jl:31-42 — on TPU the mesh *is* the binding);
    - warns when running with a single worker (parity with
      src/common.jl:25-27).

    Args:
      devices: devices to build the mesh over; defaults to all global devices.
      mesh_shape: ordered ``{axis_name: size}``; one size may be ``-1``
        (inferred). Defaults to a 1-D data-parallel mesh
        ``{config.DP_AXIS_NAME: ndevices}``. Soft-deprecated in favor of
        ``parallel=`` (which also derives partition rules, batch specs,
        and the axis names every parallelism module shares); kept for
        ad-hoc meshes. Mutually exclusive with ``parallel``.
      parallel: a :class:`~fluxmpi_tpu.parallel.ParallelConfig` (or an
        already-resolved plan) — the declarative N-D layout. The global
        mesh is the plan's mesh, the resolved plan is installed as
        :func:`global_plan` (consumed by ``make_train_step(parallel=)``,
        pipeline/ring/ulysses axis-name defaults, checkpoint manifests,
        and the ``/status`` PARALLEL board). Raises
        :class:`~fluxmpi_tpu.errors.TopologyMismatchError` when the
        plan's axes cannot cover the devices. The string ``"auto"``
        (also reachable via ``FLUXMPI_TPU_PARALLEL=auto`` when neither
        ``parallel=`` nor ``mesh_shape=`` is passed) arms the layout
        autotuner instead: the mesh comes up as the dp default and
        :func:`fluxmpi_tpu.parallel.autotune.autotune` — which needs
        the model — installs its banked or freshly-trialed winner as
        the global plan (see docs/performance.md, "Auto layout").
      distributed: force (or forbid) ``jax.distributed.initialize``; default
        auto-detects a pod slice / explicit coordinator.
      coordinator_address, num_processes, process_id: forwarded to
        ``jax.distributed.initialize`` when joining explicitly.
      verbose: print world info from every rank (reference ``verbose`` kwarg,
        src/common.jl:16).
      telemetry: wire metric emission at bring-up — a JSONL path,
        ``"console"``, a :class:`~fluxmpi_tpu.telemetry.Sink`, or a
        :class:`~fluxmpi_tpu.telemetry.MetricsRegistry` to install as the
        default (see :func:`fluxmpi_tpu.telemetry.configure`). ``None``
        defers to the ``FLUXMPI_TPU_TELEMETRY`` env var (no-op when
        unset). Applied even on already-initialized (idempotent) calls so
        a notebook can attach a sink late.
      trace: wire span tracing at bring-up — ``True`` enables recording
        into the bounded ring, a path additionally exports Chrome-trace
        JSON there at :func:`shutdown` (``{process}`` in the path is
        formatted per host); see
        :func:`fluxmpi_tpu.telemetry.tracing.configure`. ``None`` defers
        to ``FLUXMPI_TPU_TRACE``.
      watchdog: arm the hang watchdog — ``True`` or a deadline in
        seconds (stall → per-host dump of thread stacks, the collective
        flight-recorder tail, open spans, and a final registry flush;
        ``SIGUSR1`` dumps on demand); see
        :func:`fluxmpi_tpu.telemetry.watchdog.configure`. ``None``
        defers to ``FLUXMPI_TPU_WATCHDOG``. Like ``telemetry``, both are
        applied on idempotent replays too.
      preemption: install the preemption-signal handler — ``True`` (or
        ``"both"``) catches SIGTERM+SIGINT, ``"term"``/``"int"`` just
        one; the handler only sets a flag that
        :func:`~fluxmpi_tpu.parallel.train_loop` polls at dispatch
        boundaries (drain, emergency checkpoint, clean return). ``None``
        defers to ``FLUXMPI_TPU_PREEMPTION``; see
        docs/fault_tolerance.md.
      faults: arm a fault-injection schedule (grammar in
        :mod:`fluxmpi_tpu.faults`, e.g. ``"comm.allreduce@step=7"``).
        ``None`` defers to ``FLUXMPI_TPU_FAULTS``; ``False`` disarms.
      goodput: enable the run-health goodput plane — ``True`` turns on
        wall-clock badput attribution + live MFU in
        :func:`~fluxmpi_tpu.parallel.train_loop` (see
        :mod:`fluxmpi_tpu.telemetry.goodput`), or pass a
        :class:`~fluxmpi_tpu.telemetry.GoodputTracker` to install
        custom wiring. ``None`` defers to ``FLUXMPI_TPU_GOODPUT``.
      anomaly: install the anomaly detector — ``True`` = defaults (NaN
        loss/grad halt the loop cleanly, statistical rules warn),
        ``"warn"`` = observe-only, or an
        :class:`~fluxmpi_tpu.telemetry.AnomalyDetector`; on trigger an
        ``anomaly.*`` instant + a diagnostics bundle are emitted (see
        :mod:`fluxmpi_tpu.telemetry.anomaly`). ``None`` defers to
        ``FLUXMPI_TPU_ANOMALY``. All the observability/robustness specs
        are applied on idempotent replays too.
      model_stats: install the model-internals plane — ``True`` makes
        ``make_train_step`` fold a per-layer stats tree into the
        compiled program (per-layer gradient/parameter norms,
        update-to-weight ratios, nonfinite counts for NaN provenance,
        gradient noise scale on shard_map steps) that ``train_loop``
        emits as ``model.*`` metrics at flush boundaries; an int sets
        the leaf-path grouping depth, or pass a
        :class:`~fluxmpi_tpu.telemetry.ModelStats`. ``None`` defers to
        ``FLUXMPI_TPU_MODEL_STATS`` (depth/top-k knobs:
        ``FLUXMPI_TPU_MODEL_STATS_DEPTH`` /
        ``FLUXMPI_TPU_MODEL_STATS_TOPK``). See
        :mod:`fluxmpi_tpu.telemetry.modelstats`.
      compileplane: install the compile/retrace monitor — ``True``
        subscribes to ``jax.monitoring`` compile events, emits
        ``compile.*`` metrics at ``train_loop`` flush boundaries, and
        arms the ``steady_state_retrace`` anomaly rule (see
        :mod:`fluxmpi_tpu.telemetry.compileplane`); or pass a
        :class:`~fluxmpi_tpu.telemetry.CompileMonitor`. ``None`` defers
        to ``FLUXMPI_TPU_COMPILEPLANE``.
      memory: enable the HBM plane — ``True`` turns on per-device
        ``memory.*`` gauges + the peak watermark and folds the local
        peak into :class:`~fluxmpi_tpu.telemetry.TrainingMonitor`'s
        cross-host gather (see :mod:`fluxmpi_tpu.telemetry.memory`;
        OOM forensics bundles are written regardless — they ride the
        error path). ``None`` defers to ``FLUXMPI_TPU_MEMORY``.
      profile: arm anomaly-triggered auto-profiling — a logdir path
        captures one bounded XPlane window there on
        ``step_time_regression`` / ``steady_state_retrace`` triggers
        (and on ``SIGUSR2``), rate-limited to once per run; see
        :func:`fluxmpi_tpu.utils.profiling.configure_auto_profiler`.
        ``None`` defers to ``FLUXMPI_TPU_PROFILE_DIR`` (window/limit
        from ``FLUXMPI_TPU_PROFILE_SECONDS`` /
        ``FLUXMPI_TPU_PROFILE_LIMIT``).
      compile_cache: point XLA's persistent compilation cache at a
        directory (``True`` = the default location) so repeat runs —
        and, on shared storage, every host of a fleet — skip the slow
        first compile; the fused-window AOT programs land in it too
        (see :func:`enable_compile_cache`; TPU only — a warning names
        why elsewhere). ``None`` defers to
        ``FLUXMPI_TPU_COMPILE_CACHE``.
      export: start the live export plane — an in-process HTTP server
        (stdlib, daemon thread) serving Prometheus ``/metrics``, a
        ``/status`` JSON snapshot, and a ``/healthz`` liveness probe
        keyed to the watchdog's progress clock (503 when progress
        stalls past the deadline — orchestrator-restartable). ``True``
        serves on the default port (9307), a port number on that port,
        or pass an :class:`~fluxmpi_tpu.telemetry.Exporter`; ``None``
        defers to ``FLUXMPI_TPU_EXPORT_PORT`` (bind address from
        ``FLUXMPI_TPU_EXPORT_ADDR``). Poll a fleet with
        ``scripts/fluxmpi_top.py``; see docs/observability.md
        "Live export".
      serving: set the serving plane's fleet defaults — ``True`` (or a
        dict with ``slots`` / ``block_size`` / ``num_blocks`` /
        ``max_queue``) seeds
        :class:`~fluxmpi_tpu.serving.InferenceEngine` geometry,
        otherwise read from ``FLUXMPI_TPU_SERVING`` (+ ``_SLOTS`` /
        ``_BLOCK_SIZE`` / ``_BLOCKS`` / ``_QUEUE``); ``False`` resets
        the plane (any running engine stopped). See docs/serving.md.
      request_log: install the serving request-observability plane —
        ``True`` arms it in-memory (per-request lifecycle spans on the
        trace ring, KV-pool forensics, SLO burn accounting), a path
        additionally appends one schema'd JSONL record per terminal
        request there (``{process}`` formatted per host; aggregate with
        ``scripts/serving_report.py``), or pass a
        :class:`~fluxmpi_tpu.serving.RequestObserver` for custom SLO
        thresholds. ``None`` defers to ``FLUXMPI_TPU_REQUEST_LOG``
        (long burn window from ``FLUXMPI_TPU_SLO_WINDOW``); ``False``
        resets. See docs/observability.md "Serving plane".
      fleet: install the fleet plane — ``True`` arms the per-host skew
        ingredients (the monitor's gather grows the collective-block /
        flight-sequence columns, train_loop posts the FLEET board) and,
        on process 0, starts the cross-host
        :class:`~fluxmpi_tpu.telemetry.FleetCollector` scraping every
        armed host's ``/status``; a path string additionally appends
        one ``fluxmpi_tpu.fleet/v1`` snapshot per collect there (read
        back with ``scripts/fleet_report.py``), or pass a
        :class:`~fluxmpi_tpu.telemetry.FleetCollector` for custom
        hosts / interval / thresholds. ``None`` defers to
        ``FLUXMPI_TPU_FLEET`` (+ ``_FLEET_HOSTS`` / ``_FLEET_INTERVAL``);
        ``False`` resets (collector stopped). See docs/observability.md
        "Fleet plane".
      resize: arm the live-resize plane
        (:mod:`fluxmpi_tpu.fleet.resize`) — ``True``/``"1"`` arms it, a
        path string also banks one ``fluxmpi_tpu.resize/v1`` record per
        completed resize there, or pass a
        :class:`~fluxmpi_tpu.fleet.resize.ResizeCoordinator`. ``None``
        defers to ``FLUXMPI_TPU_RESIZE``; ``False`` disarms. With the
        plane armed and ``train_loop(checkpoint=...)`` attached,
        ``fluxmpi_tpu.fleet.resize.request_resize(M)`` drains the world
        at a flush boundary and hands off to an M-process relaunch.
        See docs/fault_tolerance.md "Zero-downtime ops".

    Returns:
      The global :class:`jax.sharding.Mesh`.
    """
    from .logging import fluxmpi_println  # local import: avoid cycle
    from .telemetry import anomaly as _anomaly
    from .telemetry import compileplane as _compileplane
    from .telemetry import configure as _configure_telemetry
    from .telemetry import export as _export
    from .telemetry import fleet as _fleet
    from .telemetry import goodput as _goodput
    from .telemetry import memory as _memory
    from .telemetry import modelstats as _modelstats
    from .telemetry import tracing as _tracing
    from .telemetry import watchdog as _watchdog
    from .utils import profiling as _profiling
    from . import faults as _faults_mod
    from . import serving as _serving
    from .fleet import resize as _resize
    from .serving import observe as _serving_observe

    # parallel="auto" (or FLUXMPI_TPU_PARALLEL=auto with no explicit
    # layout): arm auto mode. The mesh comes up as the 1-D dp default;
    # fluxmpi_tpu.parallel.autotune.autotune(...) later installs its
    # winner over it (same-process, pre-training) — init itself cannot
    # run trials because it does not know the model yet.
    auto_requested = False
    if isinstance(parallel, str):
        if parallel != "auto":
            raise ValueError(
                f'parallel= accepts a ParallelConfig, a ResolvedPlan, or '
                f'the string "auto", got {parallel!r}'
            )
        auto_requested = True
        parallel = None
    elif parallel is None and mesh_shape is None:
        env_parallel = os.environ.get("FLUXMPI_TPU_PARALLEL", "").strip()
        if env_parallel == "auto":
            auto_requested = True
        elif env_parallel:
            warnings.warn(
                f'ignoring FLUXMPI_TPU_PARALLEL={env_parallel!r} — the '
                f'only supported value is "auto" (pass a ParallelConfig '
                f'to init(parallel=) for an explicit layout)',
                stacklevel=2,
            )

    if _state.initialized:
        if parallel is not None and not _same_plan(parallel, _state.plan):
            # The mesh (and any installed plan) is frozen at first init:
            # silently returning the OLD layout while the caller asked
            # for a new one would leave every plan consumer
            # (make_train_step(parallel=), loader defaults, manifests)
            # quietly plan-less or stale — be loud about it.
            warnings.warn(
                "fluxmpi_tpu is already initialized; init(parallel=) "
                "cannot rebuild the global mesh on an idempotent replay "
                "— the existing mesh/plan stays. Call shutdown() first "
                "to re-init under a different ParallelConfig.",
                stacklevel=2,
            )
        _configure_telemetry(telemetry)
        _tracing.configure(trace)
        _watchdog.configure(watchdog)
        _configure_preemption(preemption)
        _faults_mod.configure(faults)
        _goodput.configure(goodput)
        _anomaly.configure(anomaly)
        _modelstats.configure(model_stats)
        _compileplane.configure(compileplane)
        _memory.configure(memory)
        _profiling.configure_auto_profiler(profile)
        _configure_compile_cache(compile_cache)
        _export.configure(export)
        _serving.configure(serving)
        _serving_observe.configure(request_log)
        _fleet.configure(fleet)
        _resize.configure(resize)
        if auto_requested:
            _state.auto_parallel = True
        if verbose:
            fluxmpi_println("fluxmpi_tpu already initialized; skipping...")
        assert _state.mesh is not None
        return _state.mesh

    if distributed is None:
        distributed = coordinator_address is not None or _should_init_distributed()
    if distributed and not _state.distributed:
        # Must run before ANY backend use (jax.devices/process_count/...)
        # or the coordinator handshake cannot happen. A failure here must be
        # loud: silently degrading a pod slice to independent single-process
        # worlds would train without gradient sync and produce wrong results.
        try:
            # CPU worlds need the gloo collectives opt-in BEFORE the
            # backend client exists, or every cross-process device
            # computation fails with "Multiprocess computations aren't
            # implemented on the CPU backend" (no-op on TPU/GPU).
            from .parallel._compat import (
                enable_cpu_cross_process_collectives,
            )

            enable_cpu_cross_process_collectives()
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
            )
            _state.distributed = True
        except RuntimeError as e:  # pragma: no cover - deployment-specific
            if "already" in str(e).lower():
                _state.distributed = True
            else:
                raise

    if parallel is not None and mesh_shape is not None:
        raise ValueError(
            "pass either parallel= (the declarative plan) or mesh_shape= "
            "(an ad-hoc mesh), not both"
        )
    devs = list(devices) if devices is not None else jax.devices()
    if parallel is not None:
        from .parallel.plan import ParallelConfig, ResolvedPlan

        if isinstance(parallel, ResolvedPlan):
            plan = parallel
            if devices is not None:
                plan_devs = {d.id for d in plan.mesh.devices.flat}
                want = {d.id for d in devs}
                if plan_devs != want:
                    raise TopologyMismatchError(
                        f"init(devices=) names {len(want)} device(s) but "
                        f"the pre-resolved plan's mesh covers device ids "
                        f"{sorted(plan_devs)} — resolve the ParallelConfig "
                        f"against those devices, or pass the config "
                        f"itself"
                    )
        elif isinstance(parallel, ParallelConfig):
            plan = parallel.resolve(devs)
        else:
            raise ValueError(
                f"parallel= must be a ParallelConfig or ResolvedPlan, "
                f"got {parallel!r}"
            )
        mesh = plan.mesh
        _state.plan = plan
        axis_names = tuple(mesh.axis_names)
        sizes = [int(s) for s in mesh.shape.values()]
    else:
        if mesh_shape is None:
            mesh_shape = {config.DP_AXIS_NAME: len(devs)}
        axis_names = tuple(mesh_shape.keys())
        sizes = list(mesh_shape.values())
        if sizes.count(-1) > 1:
            raise ValueError(
                "at most one mesh axis may have inferred size -1"
            )
        if -1 in sizes:
            known = int(np.prod([s for s in sizes if s != -1]))
            if len(devs) % known != 0:
                raise ValueError(
                    f"cannot infer mesh axis: {len(devs)} devices not "
                    f"divisible by {known}"
                )
            sizes[sizes.index(-1)] = len(devs) // known
        if int(np.prod(sizes)) != len(devs):
            raise ValueError(
                f"mesh_shape {dict(zip(axis_names, sizes))} does not cover "
                f"{len(devs)} devices"
            )
        mesh = Mesh(np.asarray(devs).reshape(sizes), axis_names)
        _state.plan = None
    _state.mesh = mesh
    _state.initialized = True
    _state.auto_parallel = auto_requested
    _configure_telemetry(telemetry)
    _tracing.configure(trace)
    _watchdog.configure(watchdog)
    _configure_preemption(preemption)
    _faults_mod.configure(faults)
    _goodput.configure(goodput)
    _anomaly.configure(anomaly)
    _modelstats.configure(model_stats)
    _compileplane.configure(compileplane)
    _memory.configure(memory)
    _profiling.configure_auto_profiler(profile)
    _configure_compile_cache(compile_cache)
    _export.configure(export)
    _serving.configure(serving)
    _serving_observe.configure(request_log)
    # After export.configure: the collector's default scrape target is
    # this host's own live exporter when FLUXMPI_TPU_FLEET_HOSTS is
    # unset, so the exporter must already be resolved.
    _fleet.configure(fleet)
    _resize.configure(resize)
    if _state.plan is not None:
        # PARALLEL board: the resolved mesh/axis sizes land on /status
        # and the parallel.* gauges the moment the plan is installed
        # (rule hit counts follow from plan.shard_state).
        from .parallel.plan import post_board

        post_board(_state.plan)

    if verbose:
        if total_workers() == 1:
            warnings.warn(
                "Using fluxmpi_tpu with only 1 worker. It might be faster to "
                "run the code without the distributed wrappers.",
                stacklevel=2,
            )
        fluxmpi_println(
            f"Initialized: {jax.process_count()} process(es), "
            f"{len(devs)} device(s), mesh axes {dict(zip(axis_names, sizes))}, "
            f"platform {devs[0].platform}"
        )
    return mesh


def is_initialized() -> bool:
    """Has the runtime been initialized? (reference: src/common.jl:6)."""
    return _state.initialized


# Reference-spelling alias (``FluxMPI.Initialized``).
Initialized = is_initialized


def shutdown() -> None:
    """Reset runtime state (test helper; analogue of ``MPI.Finalize`` in the
    reference test files, e.g. test/test_common.jl:15). Disarms the
    watchdog, exports the trace ring (when a path was configured), and
    flushes/detaches any telemetry sinks so a final partial record is
    never lost — then drops the mesh. Ordered so the trace export still
    sees the process index. The fault-tolerance plane resets with the
    runtime too: a fault schedule or preemption flag left armed across an
    init/shutdown cycle would make the next run inject faults (or
    "preempt" at its first dispatch boundary) that nobody asked for."""
    try:
        from .telemetry import shutdown as _telemetry_shutdown

        _telemetry_shutdown()
    except Exception:
        pass
    try:
        from . import faults as _faults

        _faults.clear()
    except Exception:
        pass
    uninstall_preemption_handlers()
    _state.initialized = False
    _state.mesh = None
    _state.plan = None
    _state.auto_parallel = False


def _require_init() -> None:
    if not _state.initialized:
        raise FluxMPINotInitializedError()


def local_rank() -> int:
    """Rank of this controller process (reference: src/common.jl:52-57)."""
    _require_init()
    return jax.process_index()


def total_workers() -> int:
    """Total number of data-parallel workers — global device count
    (reference: src/common.jl:64-69; there 1 worker == 1 GPU == 1 process,
    here 1 worker == 1 TPU chip)."""
    _require_init()
    return int(np.prod(list(_state.mesh.shape.values())))  # type: ignore[union-attr]


def process_index() -> int:
    """Index of this controller process in the multi-host world."""
    _require_init()
    return jax.process_index()


def process_count() -> int:
    """Number of controller processes in the multi-host world."""
    _require_init()
    return jax.process_count()


def device_count() -> int:
    """Global device count."""
    _require_init()
    return jax.device_count()


def local_device_count() -> int:
    """Devices addressable by this process."""
    _require_init()
    return jax.local_device_count()


def global_mesh() -> Mesh:
    """The mesh built by :func:`init` — the analogue of ``MPI.COMM_WORLD``
    (reference passes the world comm to every collective,
    e.g. src/optimizer.jl:21, src/synchronize.jl:16)."""
    _require_init()
    assert _state.mesh is not None
    return _state.mesh


def global_plan() -> Any:
    """The :class:`~fluxmpi_tpu.parallel.plan.ResolvedPlan` installed by
    ``init(parallel=)``, or None (uninitialized runtime, or a mesh built
    from ``mesh_shape=``/defaults). Non-raising on purpose: consumers
    (pipeline/ring/ulysses axis-name defaults, checkpoint manifests)
    fall back to the ``*_axis_name`` preferences when no plan exists."""
    return _state.plan


def auto_parallel() -> bool:
    """Was the runtime armed with ``init(parallel="auto")`` (or
    ``FLUXMPI_TPU_PARALLEL=auto``)? While True and no autotuned plan is
    installed yet, :func:`global_plan` is still None — the layout
    autotuner fills it in."""
    return _state.initialized and _state.auto_parallel


def _install_autotuned_plan(plan: Any) -> bool:
    """Install the layout autotuner's winning plan as the global plan
    (and its mesh as the global mesh). Only honored under an armed auto
    mode on an initialized runtime — a hand-pinned init must never have
    its layout swapped out from under it. Returns True when installed."""
    if not _state.initialized or not _state.auto_parallel:
        return False
    from .parallel.plan import post_board

    _state.mesh = plan.mesh
    _state.plan = plan
    post_board(plan)
    return True


def dp_axis_name() -> str:
    """Name of the data-parallel mesh axis (the installed plan's when
    ``init(parallel=)`` built the mesh, else the preference)."""
    if _state.plan is not None:
        return _state.plan.dp_axis_name
    return config.DP_AXIS_NAME
